"""Per-host telemetry collector + fleet rollup aggregation.

NEW, fleet-observability plane (ISSUE 14).  PR 7's telemetry is
strictly per-process: one JSONL per rank, read after the fact.  The
:class:`HostCollector` closes the gap live, with zero new transport:

- a daemon thread (never the train thread) incrementally tails the
  local JSONL via `telemetry.tail_records` — O(new lines) per poll,
  seek offsets surviving sink rotation;
- every ``MXTPU_OBS_ROLLUP_SECS`` it folds the window into ONE bounded
  rollup dict (step rates, share means, MFU, recent elastic events)
  and publishes it at ``obs/rollup/<rank>`` on the existing
  `distributed.gang_kv()` control plane (TcpKV or FileKV — the same
  channel heartbeats already ride);
- it also answers ``profile/req``: a control-plane request naming this
  rank triggers a bounded `jax.profiler` trace + HLO dump for N steps,
  emitting a ``profile_captured`` event with the artifact path — deep
  profiling as a KV write instead of a restart.

:class:`FleetView` is the read side: scan ``obs/rollup/*`` and compute
fleet MFU, per-rank step-interval skew, straggler attribution
(correlating `StragglerMonitor` suspicions with the named rank's own
breakdown), and the reshape/drain timeline.  The exporter and
`tools/fleet_report.py` both render from it.

Rollups are BOUNDED (one dict of scalars + a capped event list per
rank) so the control plane carries kilobytes, not logs.
"""

from __future__ import annotations

import os
import threading
import time

from .. import telemetry

#: event kinds that belong on the fleet timeline (reshape/drain/
#: straggler/serving membership) — the collector forwards the most
#: recent few of these inside its rollup
TIMELINE_EVENTS = (
    "mesh_reshape", "rank_drained", "rank_dead", "rank_rejoin",
    "elastic_recover", "straggler_suspected", "resume", "restart",
    "scale_up_proposed", "scale_down_proposed", "serving_reload",
    "serving_replica_failover", "serving_replica_spawned",
    "profile_captured",
    # integrity plane (integrity.py): corruption verdicts and the
    # quarantine/repair around them belong on the fleet timeline
    "sdc_detected", "integrity_mismatch", "rank_quarantined",
    "replay_audit", "serving_reload_rejected",
)

_TIMELINE_MAX = 16     # events carried per rollup
_WINDOW_STEPS = 64     # step records folded into the means


def rollup_secs() -> float:
    """MXTPU_OBS_ROLLUP_SECS: collector publish period (default 2s)."""
    raw = os.environ.get("MXTPU_OBS_ROLLUP_SECS")
    try:
        v = float(raw) if raw else 2.0
    except ValueError:
        v = 2.0
    return max(0.05, v)


def request_profile(kv, rank, steps=5, logdir=None):
    """Ask the collector on `rank` for a bounded profile capture:
    write the ``profile/req`` key every collector polls.  Returns the
    request id (the ``profile/done/<rank>`` ack echoes it)."""
    req_id = f"{int(time.time() * 1e3):x}-{rank}"
    kv.put_json("profile/req", {
        "id": req_id, "rank": int(rank), "steps": int(steps),
        "logdir": logdir, "t": time.time()})
    return req_id


class HostCollector:
    """Tail this host's telemetry JSONL, publish bounded rollups, and
    answer on-demand profile requests.

    ``path``: the JSONL to tail (default MXTPU_TELEMETRY_PATH).
    ``kv``: gang KV (default `distributed.gang_kv()`); None degrades
    to local-only collection (rollup() still works, nothing publishes).
    ``rank``/``world``: fleet identity (default `telemetry.identity()`).
    ``hlo_provider``: zero-arg callable returning the step program's
    HLO text (or None) — wired by the Trainer for profile dumps.
    """

    def __init__(self, path=None, kv=None, rank=None, world=None,
                 period_s=None, hlo_provider=None):
        ident = telemetry.identity()
        self.path = path or telemetry.telemetry_path()
        if kv is None:
            try:
                from .. import distributed

                kv = distributed.gang_kv()
            except Exception:
                kv = None
        self.kv = kv
        self.rank = int(rank if rank is not None
                        else ident.get("rank", 0))
        self.world = int(world if world is not None
                         else ident.get("world", 1))
        self.period_s = rollup_secs() if period_s is None \
            else max(0.05, float(period_s))
        self.hlo_provider = hlo_provider
        self.polls = 0
        self.published = 0
        self.profiles_captured = 0
        self._steps = []       # bounded window of step records
        self._events = []      # bounded window of timeline events
        self._requests = 0
        self._request_queue_us = 0.0
        self._steps_total = 0
        self._skipped_total = 0
        self._attestations = 0
        self._integrity_mismatches = 0
        self._last_profile_id = None
        self._stop = threading.Event()
        self._thread = None

    # -- collection ------------------------------------------------------------

    def _fold(self, records):
        for rec in records:
            kind = rec.get("type")
            if kind == "step":
                self._steps_total += 1
                if rec.get("skipped"):
                    self._skipped_total += 1
                self._steps.append(rec)
                del self._steps[:-_WINDOW_STEPS]
            elif kind == "event":
                if rec.get("event") in TIMELINE_EVENTS:
                    self._events.append(rec)
                    del self._events[:-_TIMELINE_MAX]
            elif kind == "request":
                self._requests += 1
                self._request_queue_us += float(rec.get("queue_us", 0.0))
            elif kind == "integrity":
                self._attestations += 1
                if not rec.get("ok", True):
                    self._integrity_mismatches += 1

    def rollup(self) -> dict:
        """The bounded per-rank summary published to the control
        plane.  Scalars + a capped event list — never raw logs."""
        steps = self._steps
        n = len(steps)

        def mean(key):
            vals = [s[key] for s in steps
                    if isinstance(s.get(key), (int, float))]
            return sum(vals) / len(vals) if vals else None

        shares = {}
        for k in ("data", "host_prep", "dispatch", "readback",
                  "collective", "other"):
            vals = [s["shares"][k] for s in steps
                    if isinstance(s.get("shares"), dict)
                    and k in s["shares"]]
            if vals:
                shares[k] = round(sum(vals) / len(vals), 4)
        out = {
            "rank": self.rank, "world": self.world, "t": time.time(),
            "run": telemetry.run_id(),
            "steps_total": self._steps_total,
            "steps_window": n,
            "skipped_total": self._skipped_total,
            "last_step": steps[-1].get("step") if n else None,
            "interval_us_mean": mean("interval_us"),
            "wall_us_mean": mean("wall_us"),
            "mfu_mean": mean("mfu"),
            "bubble_fraction_mean": mean("bubble_fraction"),
            "shares": shares,
            "requests_total": self._requests,
            "request_queue_us_mean": round(
                self._request_queue_us / self._requests, 1)
            if self._requests else None,
            "attestations": self._attestations,
            "integrity_mismatches": self._integrity_mismatches,
            "events": [self._event_brief(e) for e in self._events],
        }
        return out

    @staticmethod
    def _event_brief(e):
        brief = {"event": e.get("event"), "t": e.get("t")}
        for k in ("rank", "world", "epoch", "step", "members",
                  "planned", "mean_collective_share", "laggard_step",
                  "path", "steps", "generation", "kind", "corrupt",
                  "reason"):
            if e.get(k) is not None:
                brief[k] = e[k]
        return brief

    def poll_once(self):
        """One collector tick: tail the log, answer profile requests,
        publish the rollup.  Runs on the collector thread (or directly
        from tests)."""
        self.polls += 1
        if self.path:
            self._fold(telemetry.tail_records(self.path))
        self._check_profile_request()
        if self.kv is not None:
            try:
                self.kv.put_json(f"obs/rollup/{self.rank}",
                                 self.rollup())
                self.published += 1
            except Exception:
                pass           # observability must never kill training
        return self.published

    # -- on-demand profiling ---------------------------------------------------

    def _check_profile_request(self):
        if self.kv is None:
            return
        try:
            req = self.kv.get_json("profile/req")
        except Exception:
            return
        if not isinstance(req, dict) or req.get("rank") != self.rank:
            return
        req_id = req.get("id")
        if req_id is not None and req_id == self._last_profile_id:
            return
        self._last_profile_id = req_id
        try:
            self._capture_profile(req)
        finally:
            try:
                self.kv.delete("profile/req")
            except Exception:
                pass

    def _capture_profile(self, req):
        """Bounded `jax.profiler` capture: trace until N more steps
        land in the tailed log (or the time budget runs out), then an
        HLO dump next to it.  Runs on the collector thread — the train
        thread never blocks."""
        steps = max(1, int(req.get("steps", 5)))
        logdir = req.get("logdir") or os.path.join(
            os.environ.get("MXTPU_PROFILE_DIR", "/tmp/mxtpu_profile"),
            f"rank{self.rank}-{int(time.time())}")
        os.makedirs(logdir, exist_ok=True)
        budget_s = float(os.environ.get("MXTPU_PROFILE_BUDGET_S", 30.0))
        start_total = self._steps_total
        traced = False
        try:
            import jax

            jax.profiler.start_trace(logdir)
            traced = True
        except Exception:
            pass
        # the budget bounds the step WAIT — start_trace itself may pay
        # a multi-second one-time backend init
        t0 = time.time()
        try:
            while (self._steps_total - start_total < steps
                   and time.time() - t0 < budget_s
                   and not self._stop.is_set()):
                time.sleep(0.02)
                if self.path:
                    self._fold(telemetry.tail_records(self.path))
        finally:
            if traced:
                try:
                    import jax

                    jax.profiler.stop_trace()
                except Exception:
                    pass
        hlo = None
        if self.hlo_provider is not None:
            try:
                hlo = self.hlo_provider()
            except Exception:
                hlo = None
        if hlo:
            with open(os.path.join(logdir, "step_hlo.txt"), "w",
                      encoding="utf-8") as f:
                f.write(hlo)
        self.profiles_captured += 1
        captured = self._steps_total - start_total
        telemetry.event("profile_captured", rank=self.rank,
                        steps=captured, path=logdir,
                        traced=traced, hlo=bool(hlo))
        if self.kv is not None:
            try:
                self.kv.put_json(f"profile/done/{self.rank}", {
                    "id": req.get("id"), "rank": self.rank,
                    "steps": captured, "path": logdir,
                    "t": time.time()})
            except Exception:
                pass

    # -- lifecycle -------------------------------------------------------------

    def start(self):
        if self._thread is not None:
            return self
        self._thread = threading.Thread(target=self._loop,
                                        name="mxtpu-obs-collector",
                                        daemon=True)
        self._thread.start()
        return self

    def _loop(self):
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception:    # noqa: BLE001 — keep collecting
                pass
            self._stop.wait(self.period_s)

    def close(self, timeout=5.0):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None


class FleetView:
    """Aggregate the per-rank rollups into one fleet picture."""

    def __init__(self, kv):
        self.kv = kv
        self.rollups = {}

    def refresh(self):
        """Re-scan ``obs/rollup/*`` → {rank: rollup}."""
        import json as _json

        out = {}
        for key, raw in self.kv.scan("obs/rollup"):
            try:
                rec = _json.loads(raw.decode("utf-8")
                                  if isinstance(raw, bytes) else raw)
            except (ValueError, AttributeError):
                continue
            if isinstance(rec, dict) and rec.get("rank") is not None:
                out[int(rec["rank"])] = rec
        self.rollups = out
        return out

    def summary(self) -> dict:
        """Fleet MFU (step-weighted), interval skew, straggler
        attribution, and the merged event timeline."""
        rollups = self.rollups
        ranks = sorted(rollups)
        intervals = {r: rollups[r].get("interval_us_mean")
                     for r in ranks
                     if rollups[r].get("interval_us_mean")}
        mfu_num = mfu_den = 0.0
        for r in ranks:
            mfu = rollups[r].get("mfu_mean")
            w = rollups[r].get("steps_window") or 0
            if mfu is not None and w:
                mfu_num += mfu * w
                mfu_den += w
        skew = None
        slowest = None
        if intervals:
            slowest = max(intervals, key=intervals.get)
            lo = min(intervals.values())
            if lo > 0:
                skew = max(intervals.values()) / lo
        timeline = []
        for r in ranks:
            for e in rollups[r].get("events", []):
                timeline.append(dict(e, observed_by=r))
        timeline.sort(key=lambda e: e.get("t") or 0.0)
        return {
            "ranks": ranks,
            "world": max((rollups[r].get("world") or 0
                          for r in ranks), default=0),
            "steps_total": sum(rollups[r].get("steps_total") or 0
                               for r in ranks),
            "fleet_mfu": round(mfu_num / mfu_den, 6) if mfu_den else None,
            "interval_us": {r: round(v, 1)
                            for r, v in intervals.items()},
            "interval_skew": round(skew, 3) if skew else None,
            "slowest_rank": slowest,
            "stragglers": self._stragglers(),
            "attestations": sum(rollups[r].get("attestations") or 0
                                for r in ranks),
            "integrity_mismatches": sum(
                rollups[r].get("integrity_mismatches") or 0
                for r in ranks),
            "timeline": timeline,
        }

    def _stragglers(self):
        """Correlate StragglerMonitor suspicions with the NAMED rank's
        own interval breakdown: the suspicion says "rank R holds the
        collective up"; R's rollup says where R's time actually goes
        and how much slower than the fleet median it runs."""
        rollups = self.rollups
        med = self._median([v.get("interval_us_mean") for v in
                            rollups.values()
                            if v.get("interval_us_mean")])
        out = []
        seen = set()
        for r in sorted(rollups):
            for e in rollups[r].get("events", []):
                if e.get("event") != "straggler_suspected":
                    continue
                named = e.get("rank")
                if named is None or named in seen:
                    continue
                seen.add(named)
                entry = {"rank": named, "suspected_by": r,
                         "mean_collective_share":
                             e.get("mean_collective_share")}
                target = rollups.get(named)
                if target:
                    shares = target.get("shares") or {}
                    if shares:
                        bucket = max(shares, key=shares.get)
                        entry["stall_bucket"] = bucket
                        entry["stall_share"] = shares[bucket]
                    iv = target.get("interval_us_mean")
                    if iv and med:
                        entry["slowdown_vs_median"] = round(iv / med, 3)
                out.append(entry)
        return out

    @staticmethod
    def _median(vals):
        vals = sorted(v for v in vals if v is not None)
        if not vals:
            return None
        n = len(vals)
        return vals[n // 2] if n % 2 else \
            (vals[n // 2 - 1] + vals[n // 2]) / 2.0
