"""Network visualization.

Reference parity: python/mxnet/visualization.py — print_summary (per-layer
params table) and plot_network (graphviz).  Here summary introspects gluon
Blocks; plot_network renders the jaxpr of a hybridized block when graphviz
is available and degrades to text otherwise.
"""

from __future__ import annotations

import numpy as _np


def block_summary(block, *inputs):
    """Per-layer summary of a gluon Block (reference: Block.summary)."""
    summary = []

    def walk(blk, name, depth):
        n_params = 0
        for p in blk._reg_params.values():
            try:
                n_params += int(_np.prod(p.shape))
            except Exception:
                pass
        summary.append((name or blk.name, type(blk).__name__, n_params,
                        depth))
        for child_name, child in blk._children.items():
            walk(child, f"{name}.{child_name}" if name else child_name,
                 depth + 1)

    walk(block, "", 0)
    total = 0
    lines = [f"{'Layer':<44}{'Type':<24}{'Params':>12}",
             "-" * 80]
    for name, tname, n, depth in summary:
        total += n
        lines.append(f"{'  ' * depth + (name or tname):<44}{tname:<24}"
                     f"{n:>12}")
    lines.append("-" * 80)
    lines.append(f"{'Total params':<68}{total:>12}")
    out = "\n".join(lines)
    print(out)
    return out


def print_summary(symbol_or_block, shape=None, line_length=120,
                  positions=(.44, .64, .74, 1.)):
    """Reference: mx.viz.print_summary."""
    from .gluon.block import Block

    if isinstance(symbol_or_block, Block):
        return block_summary(symbol_or_block)
    # symbol path: walk graph nodes
    sym = symbol_or_block
    lines = [f"{'Op':<40}{'Name':<40}", "-" * 80]
    for node in sym.list_nodes():
        lines.append(f"{node.get('op', 'null'):<40}"
                     f"{node.get('name', ''):<40}")
    out = "\n".join(lines)
    print(out)
    return out


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 node_attrs=None, hide_weights=True):
    """Reference: mx.viz.plot_network (graphviz).  Degrades to a text
    rendering when graphviz is unavailable (zero-egress image)."""
    try:
        import graphviz  # noqa: F401
    except ImportError:
        return print_summary(symbol)
    dot = graphviz.Digraph(name=title)
    for node in symbol.list_nodes():
        op = node.get("op", "null")
        name = node.get("name", "")
        if hide_weights and op == "null" and (
                name.endswith("weight") or name.endswith("bias")):
            continue
        dot.node(name, f"{op}\n{name}")
        for src in node.get("inputs", []):
            dot.edge(str(src), name)
    return dot
