"""Attribute scoping for symbol construction (reference:
python/mxnet/attribute.py — ``mx.AttrScope``).

``with mx.AttrScope(ctx_group='stage1', lr_mult='0.1'):`` attaches the
given attributes to every Symbol node created inside the scope — the
mechanism the reference's ``group2ctx`` model-parallel placement and
per-layer lr/wd multipliers ride on.  Here the attrs land in the node's
``_attr_dict`` (readable via ``Symbol.attr``; ``subgraph.py`` partition
properties and ``module`` lr_mult handling consume them).
"""

from __future__ import annotations

import threading


class _Local(threading.local):
    def __init__(self):
        self.stack: list = []


_LOCAL = _Local()


class AttrScope:
    """Scope attributes applied to symbols created within (nestable;
    inner scopes override outer keys)."""

    def __init__(self, **kwargs):
        # reference contract: attribute values must be strings (they
        # serialize into symbol.json verbatim; non-strings would change
        # type across a save/load round trip)
        for k, v in kwargs.items():
            if v is not None and not isinstance(v, str):
                raise ValueError(
                    f"AttrScope: attribute {k}={v!r} must be a string "
                    f"(got {type(v).__name__}) — reference "
                    f"attribute.py enforces the same")
        self._attr = {k: v for k, v in kwargs.items() if v is not None}

    def __enter__(self):
        merged = dict(current_attrs())
        merged.update(self._attr)
        _LOCAL.stack.append(merged)
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        _LOCAL.stack.pop()
        return False


def current_attrs():
    """The attr dict the innermost active scope contributes ({} if no
    scope is active)."""
    return _LOCAL.stack[-1] if _LOCAL.stack else {}
