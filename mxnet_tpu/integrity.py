"""Integrity plane: silent-data-corruption detection, cross-replica
state attestation, and corrupt-rank quarantine (ISSUE 16).

Every failure the resilience stack survives is *loud* — a dead
heartbeat (ElasticGang), a NaN gradient (numerics.StepGuard), a torn
file (checkpoint CRCs).  The dominant unhandled hazard at fleet scale
is *silent* corruption: a flipped bit in a parameter shard, a
defective core producing subtly wrong math, a replica whose state has
drifted — every rank keeps reporting "healthy" while training
diverges.  The whole-program capture discipline (gluon/captured.py)
makes cheap detection possible: dp replicas of a captured step are
bitwise-identical by construction, so ANY cross-replica fingerprint
mismatch is corruption by definition, and a deterministically
re-executed step is a free ground-truth oracle.

Three detection tiers, riding entirely on existing substrates:

- **Tier 1 — cross-replica attestation** (`IntegrityPlane.attest`):
  every ``MXTPU_INTEGRITY_EVERY`` (default 50) steps each rank
  publishes a fingerprint of its full parameter+optimizer-state pytree
  at ``integrity/<epoch>/<step>/<rank>`` on the gang KV (the channel
  heartbeats already ride).  The fingerprint is computed *inside* the
  captured step (`fingerprint_arrays` as an extra program output gated
  by a traced ``attest`` predicate — zero extra dispatches) and read
  back with the existing StepGuard readback.  Replicas that must be
  bitwise-equal vote: the majority value is truth, the minority
  rank(s) are corrupt.

- **Tier 2 — shadow replay audit** (`IntegrityPlane.retain` /
  ``audit``): re-execute the last attested step from the retained
  pre-step snapshot through the same step function and compare
  fingerprints.  Works at world size 1, and *classifies* the
  corruption: replay disagreeing with the live result means the live
  state was mutated after the fact (``kind="memory"``, e.g. a bit
  flip); replay agreeing with itself while peers disagree means the
  math itself is wrong deterministically (``kind="compute"``, a bad
  core).

- **Tier 3 — lineage ledger** (`IntegrityLedger`): each attestation is
  hash-chained onto the previous one in a per-run JSONL ledger (next
  to the autotune tuning DB).  `checkpoint.AsyncCheckpointer` stamps
  the ledger head into MANIFEST.json and restore verifies provenance
  (`verify_provenance`) — a checkpoint audits back to its origin, not
  just its transport CRCs.

On confirmed corruption the plane emits ``sdc_detected{rank, kind,
step}``, and `quarantine` turns the verdict into a
`resilience.RankFailure` so the existing ElasticGang evict/amendment
path reshapes the gang, restores the corrupt rank's state from a buddy
snapshot or the manifest, and grows back.

Fingerprint math: every leaf is reinterpreted as uint32 words and
folded as ``sum(word[i] * (2*i+1) * salt(leaf))`` into two mod-2^32
accumulators with independent per-leaf salts.  All weights are odd,
hence invertible mod 2^32, so any single-bit flip in any word changes
the sum; modular addition is exact and associative, so the jitted
device reduction (`fingerprint_arrays`) and the numpy host mirror
(`fingerprint_host`) agree bitwise regardless of reduction order —
pinned by tests/test_integrity.py.

Env knobs (docs/env_vars.md): ``MXTPU_INTEGRITY`` (default off),
``MXTPU_INTEGRITY_EVERY`` (50), ``MXTPU_INTEGRITY_LEDGER`` (ledger
path override), ``MXTPU_INTEGRITY_TIMEOUT`` (peer-wait seconds, 5).
Fault sites (docs/resilience.md): ``bit_flip_param:K`` /
``bit_flip_grad:K`` (flip one bit on rank K) and ``bad_core:K``
(rank K computes a deterministically wrong answer).
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time

try:
    from .base import MXNetError
except ImportError:     # standalone load (tools, bench orchestrator)
    MXNetError = RuntimeError

_SALT_LO = 0x9E3779B1   # odd golden-ratio constants: per-leaf salts
_SALT_HI = 0x85EBCA77   # stay odd (odd * odd), hence invertible
_MASK32 = 0xFFFFFFFF


# -- env plumbing --------------------------------------------------------------

def enabled() -> bool:
    """MXTPU_INTEGRITY gate (default off): when on, the captured step
    computes the state fingerprint in-program and the Trainer attests
    on the plane attached via ``Trainer.attach_integrity``."""
    return os.environ.get("MXTPU_INTEGRITY", "").lower() \
        in ("1", "true", "on", "yes")


def fingerprint_enabled() -> bool:
    """Alias read by `gluon.captured.get_step` — the flag joins the
    capture cache key (a toggled value must re-trace: the program
    grows/loses the fingerprint output)."""
    return enabled()


def attest_every(default=50) -> int:
    """MXTPU_INTEGRITY_EVERY: attestation period in steps."""
    try:
        v = int(os.environ.get("MXTPU_INTEGRITY_EVERY", default))
    except ValueError:
        v = default
    return max(1, v)


def peer_timeout(default=5.0) -> float:
    """MXTPU_INTEGRITY_TIMEOUT: how long `attest` waits for layout-mate
    fingerprints before voting on what arrived."""
    try:
        v = float(os.environ.get("MXTPU_INTEGRITY_TIMEOUT", default))
    except ValueError:
        v = default
    return max(0.0, v)


def ledger_path():
    """Ledger location: MXTPU_INTEGRITY_LEDGER when set, else
    ``integrity_ledger.jsonl`` next to the autotune tuning DB (the
    MXTPU_TUNE_DB dir / MXTPU_COMPILE_CACHE_DIR), else None (ledger
    off — attestation still works, provenance stamping degrades)."""
    p = os.environ.get("MXTPU_INTEGRITY_LEDGER")
    if p:
        return p
    db = os.environ.get("MXTPU_TUNE_DB")
    if db:
        return os.path.join(os.path.dirname(db) or ".",
                            "integrity_ledger.jsonl")
    cache = os.environ.get("MXTPU_COMPILE_CACHE_DIR")
    if cache:
        return os.path.join(cache, "integrity_ledger.jsonl")
    return None


def self_rank(default=0) -> int:
    """This process's fleet rank (MXTPU_WORKER_RANK, the launch.py
    identity every other subsystem keys on) — what the rank-targeted
    SDC fault sites compare against when no gang rank is supplied."""
    try:
        return int(os.environ.get("MXTPU_WORKER_RANK", default))
    except ValueError:
        return default


def _tel_event(name, /, **fields):
    """Import-guarded telemetry event (this module also loads
    standalone, e.g. from tools/).  The event name is positional-only
    so a ``kind`` detail field passes through cleanly."""
    try:
        from . import telemetry
    except ImportError:
        return
    telemetry.event(name, **fields)


def _tel_integrity(**fields):
    try:
        from . import telemetry
    except ImportError:
        return
    telemetry.integrity_record(**fields)


# -- fingerprint math ----------------------------------------------------------

def _salts(j):
    lo = (_SALT_LO * (2 * j + 1)) & _MASK32
    hi = (_SALT_HI * (2 * j + 1)) & _MASK32
    return lo, hi


def fingerprint_arrays(arrs):
    """Pure, traceable fingerprint reduction over arrays → ``(2,)``
    uint32 ``[lo, hi]``.  The ONE home of the device-side math: the
    whole-step capture inlines it as an extra program output, so the
    fingerprint costs zero extra dispatches.  Per leaf ``j``, words are
    weighted ``(2*i+1) * salt_j`` (odd → any single-bit flip changes
    the sum mod 2^32); the iota fuses into the reduction, nothing is
    materialized."""
    import jax
    import jax.numpy as jnp

    lo = jnp.zeros((), jnp.uint32)
    hi = jnp.zeros((), jnp.uint32)
    for j, a in enumerate(arrs):
        r = jnp.asarray(a)
        if r.size == 0:
            continue
        w = _device_words(r)
        idx = jax.lax.iota(jnp.uint32, w.size)
        base = w * (idx * jnp.uint32(2) + jnp.uint32(1))
        slo, shi = _salts(j)
        lo = lo + jnp.sum(base * jnp.uint32(slo), dtype=jnp.uint32)
        hi = hi + jnp.sum(base * jnp.uint32(shi), dtype=jnp.uint32)
    return jnp.stack([lo, hi])


def _device_words(r):
    """Reinterpret one device array as a flat uint32 word vector."""
    import jax.numpy as jnp
    from jax import lax

    if r.dtype == jnp.bool_:
        return r.astype(jnp.uint32).reshape(-1)
    size = jnp.dtype(r.dtype).itemsize
    if size == 4:
        return lax.bitcast_convert_type(r, jnp.uint32).reshape(-1)
    if size == 2:
        return lax.bitcast_convert_type(r, jnp.uint16) \
            .astype(jnp.uint32).reshape(-1)
    if size == 1:
        return lax.bitcast_convert_type(r, jnp.uint8) \
            .astype(jnp.uint32).reshape(-1)
    # 8-byte leaves: bitcast appends a trailing word dim (low word
    # first on little-endian hosts, matching the numpy mirror)
    return lax.bitcast_convert_type(r, jnp.uint32).reshape(-1)


def fingerprint_pytree(tree):
    """`fingerprint_arrays` over ``jax.tree_util.tree_leaves(tree)``."""
    import jax

    return fingerprint_arrays(jax.tree_util.tree_leaves(tree))


def _host_words(a):
    import numpy as np

    a = np.ascontiguousarray(a)
    if a.dtype == np.bool_:
        return a.astype(np.uint32).ravel()
    size = a.dtype.itemsize
    if size == 4:
        return a.view(np.uint32).ravel()
    if size == 2:
        return a.view(np.uint16).ravel().astype(np.uint32)
    if size == 1:
        return a.view(np.uint8).ravel().astype(np.uint32)
    if size % 4 == 0:
        return a.view(np.uint32).ravel()
    return a.astype(np.float32).view(np.uint32).ravel()


def fingerprint_host(tree) -> int:
    """Numpy mirror of `fingerprint_pytree`, already combined into one
    u64 int — bitwise-identical to `combine(device_fp)` for the same
    leaves (same weights, and mod-2^32 addition is order-free)."""
    import numpy as np

    try:
        import jax

        leaves = jax.tree_util.tree_leaves(tree)
    except ImportError:
        leaves = _py_leaves(tree)
    lo = hi = 0
    for j, a in enumerate(leaves):
        a = np.asarray(a)
        if a.size == 0:
            continue
        w = _host_words(a).astype(np.uint64)
        idx = np.arange(w.size, dtype=np.uint64)
        base = (w * (idx * 2 + 1)) & _MASK32
        slo, shi = _salts(j)
        lo = (lo + int(np.sum((base * slo) & _MASK32) & _MASK32)) \
            & _MASK32
        hi = (hi + int(np.sum((base * shi) & _MASK32) & _MASK32)) \
            & _MASK32
    return (hi << 32) | lo


def _py_leaves(tree):
    """Deterministic jax-free leaf flattening (dicts by sorted key) for
    standalone consumers; matches tree_leaves for the list/tuple/dict
    pytrees the numpy gang tests use."""
    if isinstance(tree, dict):
        out = []
        for k in sorted(tree):
            out.extend(_py_leaves(tree[k]))
        return out
    if isinstance(tree, (list, tuple)):
        out = []
        for v in tree:
            out.extend(_py_leaves(v))
        return out
    return [tree]


def combine(fp2) -> int:
    """Fold a host-read ``(2,)`` uint32 fingerprint into one u64."""
    import numpy as np

    v = np.asarray(fp2)
    return (int(v[1]) << 32) | int(v[0])


def fp_hex(fp: int) -> str:
    return f"{int(fp):016x}"


# NOTE: the host mirror must wrap ``base`` to 32 bits BEFORE the salt
# multiply — the device computes base = w * (2i+1) IN uint32, so the
# wrap happens there implicitly.  (w*(2i+1)) mod 2^32 then *salt mod
# 2^32 equals the device's uint32 chain because products mod 2^32
# compose.


# -- lineage ledger (tier 3) ---------------------------------------------------

_GENESIS = "0" * 64


class IntegrityLedger:
    """Hash-chained JSONL attestation ledger.

    Each line: ``{"step", "epoch", "rank", "fp", "prev", "hash", "t",
    "run"}`` where ``hash = sha256(prev + canonical-json(entry sans
    hash))``.  `head()` is the newest hash — `AsyncCheckpointer` stamps
    it into MANIFEST.json so `verify_provenance` can audit a restored
    checkpoint back to an attestation this process actually chained.
    Appends are serialized and fsync'd line-at-a-time (same durability
    discipline as the telemetry sink)."""

    def __init__(self, path):
        self.path = path
        self._lock = threading.Lock()
        self._head = None

    def head(self):
        """Newest chain hash, or None on an empty/absent ledger."""
        with self._lock:
            if self._head is None:
                entries = self.entries()
                self._head = entries[-1]["hash"] if entries else None
            return self._head

    def entries(self):
        """All parseable ledger lines, oldest first (torn tail lines
        are skipped, never fatal)."""
        if not self.path or not os.path.exists(self.path):
            return []
        out = []
        with open(self.path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict) and rec.get("hash"):
                    out.append(rec)
        return out

    @staticmethod
    def _entry_hash(prev, body):
        payload = json.dumps(body, sort_keys=True,
                             separators=(",", ":"))
        return hashlib.sha256(
            (prev + payload).encode("utf-8")).hexdigest()

    def append(self, step, fp, rank=0, epoch=0, run=None):
        """Chain one attestation; returns the entry (with its hash)."""
        if not self.path:
            return None
        with self._lock:
            prev = self._head
            if prev is None:
                entries = self.entries()
                prev = entries[-1]["hash"] if entries else _GENESIS
            body = {"step": int(step), "epoch": int(epoch),
                    "rank": int(rank), "fp": fp_hex(fp),
                    "prev": prev, "t": time.time()}
            if run is not None:
                body["run"] = run
            entry = dict(body, hash=self._entry_hash(prev, body))
            d = os.path.dirname(self.path)
            if d:
                os.makedirs(d, exist_ok=True)
            with open(self.path, "a", encoding="utf-8") as f:
                f.write(json.dumps(entry, sort_keys=True,
                                   separators=(",", ":")) + "\n")
                f.flush()
                os.fsync(f.fileno())
            self._head = entry["hash"]
            return entry

    def verify_chain(self):
        """Recompute every hash link; returns (ok, reason)."""
        prev = _GENESIS
        for i, entry in enumerate(self.entries()):
            body = {k: v for k, v in entry.items() if k != "hash"}
            if body.get("prev") != prev:
                return False, f"entry {i}: prev {body.get('prev')!r} " \
                              f"does not chain onto {prev!r}"
            if self._entry_hash(prev, body) != entry["hash"]:
                return False, f"entry {i}: hash mismatch (ledger " \
                              f"tampered or torn mid-line)"
            prev = entry["hash"]
        return True, None

    def has_hash(self, h):
        if not h:
            return False
        return any(e.get("hash") == h for e in self.entries())


_LEDGER = None
_LEDGER_LOCK = threading.Lock()


def get_ledger():
    """Process-wide ledger for the current `ledger_path()` (None when
    no path resolves)."""
    global _LEDGER
    path = ledger_path()
    if path is None:
        return None
    with _LEDGER_LOCK:
        if _LEDGER is None or _LEDGER.path != path:
            _LEDGER = IntegrityLedger(path)
        return _LEDGER


def reset():
    """Drop the cached ledger handle (test isolation)."""
    global _LEDGER
    with _LEDGER_LOCK:
        _LEDGER = None


def ledger_head():
    """Current chain head for manifest stamping, or None."""
    led = get_ledger()
    return None if led is None else led.head()


def manifest_stamp():
    """The ``integrity`` block `checkpoint._write_manifest` embeds, or
    None when no ledger is configured / nothing attested yet."""
    led = get_ledger()
    if led is None:
        return None
    head = led.head()
    if head is None:
        return None
    return {"ledger_head": head, "ledger_path": led.path}


def verify_provenance(manifest):
    """Audit a manifest's integrity stamp against the local ledger.

    Returns (ok, reason).  Lenient where it must be — an unstamped
    manifest (pre-integrity writer) or an absent ledger (fresh machine,
    checkpoint shipped in) passes with a reason string — but a stamp
    that names a hash the ledger does NOT contain fails closed: the
    checkpoint claims a lineage this host has no record of."""
    stamp = manifest.get("integrity") if isinstance(manifest, dict) \
        else None
    if not isinstance(stamp, dict) or not stamp.get("ledger_head"):
        return True, "manifest carries no integrity stamp"
    led = get_ledger()
    if led is None or not os.path.exists(led.path or ""):
        return True, "no local ledger to audit against"
    ok, reason = led.verify_chain()
    if not ok:
        return False, f"ledger chain invalid: {reason}"
    if not led.has_hash(stamp["ledger_head"]):
        return False, (f"manifest ledger head "
                       f"{stamp['ledger_head'][:12]}... not present in "
                       f"{led.path}")
    return True, None


# -- tier 1 + 2: the plane -----------------------------------------------------

class IntegrityPlane:
    """Per-rank attestation driver.

    ``kv``: gang KV (FileKV/TcpKV — `distributed.gang_kv()` by
    default; None degrades to solo mode where only the ledger and the
    replay audit operate).  ``peers``: the ranks whose state must be
    bitwise-equal to ours (dp replicas; tp/fsdp shards pass their
    layout-mates).  Default: all of ``range(world)``."""

    def __init__(self, rank=0, world=1, kv=None, peers=None, every=None,
                 epoch=0, ledger=None, timeout=None, run=None):
        self.rank = int(rank)
        self.world = int(world)
        self.kv = kv
        self.peers = sorted(set(int(r) for r in peers)) \
            if peers is not None else list(range(self.world))
        if self.rank not in self.peers:
            self.peers = sorted(self.peers + [self.rank])
        self.every = attest_every() if every is None else max(1, int(every))
        self.epoch = int(epoch)
        self.timeout = peer_timeout() if timeout is None else float(timeout)
        self.ledger = get_ledger() if ledger is None else ledger
        self.run = run
        self.attestations = 0
        self.mismatches = 0
        self.replays = 0
        self.last_verdict = None
        self._retained = {}          # step -> (state, inputs)

    # -- schedule ---------------------------------------------------------------

    def due(self, step) -> bool:
        return step is not None and int(step) % self.every == 0

    # -- tier 2 retention -------------------------------------------------------

    def retain(self, step, state, inputs=None):
        """Retain the PRE-step state (host copies) + the step's inputs
        for shadow replay.  Bounded to the most recent retention — the
        audit only ever replays the last attested step."""
        self._retained = {int(step): (state, inputs)}

    def retained(self, step=None):
        if step is not None:
            return self._retained.get(int(step))
        if not self._retained:
            return None
        s = max(self._retained)
        return (s,) + self._retained[s]

    # -- tier 1 attestation -----------------------------------------------------

    def _key(self, epoch, step, rank):
        return f"integrity/{epoch}/{step}/{rank}"

    def publish(self, step, fp, epoch=None):
        epoch = self.epoch if epoch is None else int(epoch)
        if self.kv is not None:
            self.kv.put_json(self._key(epoch, step, self.rank), {
                "rank": self.rank, "step": int(step), "epoch": epoch,
                "fp": fp_hex(fp), "t": time.time()})
        if self.ledger is not None:
            self.ledger.append(step, fp, rank=self.rank, epoch=epoch,
                               run=self.run)

    def _gather(self, step, epoch):
        """Poll the KV until every peer published (or timeout):
        {rank: fp_hex}."""
        got = {}
        want = [r for r in self.peers]
        deadline = time.monotonic() + self.timeout
        while True:
            for r in want:
                if r in got:
                    continue
                try:
                    rec = self.kv.get_json(self._key(epoch, step, r))
                except Exception:
                    rec = None
                if isinstance(rec, dict) and rec.get("fp"):
                    got[r] = rec["fp"]
            if len(got) == len(want) or time.monotonic() >= deadline:
                return got
            time.sleep(0.005)

    def attest(self, step, fp, epoch=None):
        """One attestation round: publish, gather layout-mates, vote.

        Returns the verdict dict ``{step, epoch, fp, ok, corrupt,
        tie, votes, self_corrupt, absent}``.  Majority is truth; the
        minority rank(s) are corrupt.  A two-way tie (possible only
        with an even quorum) is reported ``ok=False, tie=True`` with
        no rank named — the replay audit is the tie-breaker.  Emits
        one ``integrity`` telemetry record per round; on a mismatch
        the lowest healthy voter additionally emits
        ``integrity_mismatch`` and one ``sdc_detected`` per corrupt
        rank (kind refined later by `audit`)."""
        step = int(step)
        epoch = self.epoch if epoch is None else int(epoch)
        self.attestations += 1
        self.publish(step, fp, epoch=epoch)
        mine = fp_hex(fp)
        votes = {self.rank: mine}
        if self.kv is not None and len(self.peers) > 1:
            votes.update(self._gather(step, epoch))
        tally = {}
        for r, v in votes.items():
            tally.setdefault(v, []).append(r)
        ranked = sorted(tally.items(),
                        key=lambda kv_: (-len(kv_[1]), min(kv_[1])))
        best_fp, best_ranks = ranked[0]
        tie = len(ranked) > 1 and len(ranked[1][1]) == len(best_ranks)
        ok = len(ranked) == 1
        corrupt = [] if ok or tie else sorted(
            r for v, rs in ranked[1:] for r in rs)
        absent = sorted(set(self.peers) - set(votes))
        verdict = {
            "step": step, "epoch": epoch, "fp": mine, "ok": ok,
            "tie": tie, "corrupt": corrupt, "votes": votes,
            "absent": absent, "self_corrupt": self.rank in corrupt,
        }
        self.last_verdict = verdict
        if not ok:
            self.mismatches += 1
        _tel_integrity(step=step, fp=mine, ok=ok, epoch=epoch,
                       peers=len(votes), corrupt=corrupt or None,
                       rank=self.rank)
        healthy = tally.get(best_fp, [])
        if not ok and not tie and healthy and \
                self.rank == min(healthy):
            # one announcer per verdict (the amendment discipline:
            # lowest healthy member speaks for the quorum)
            _tel_event("integrity_mismatch", step=step, epoch=epoch,
                       corrupt=corrupt, votes=len(votes))
            for r in corrupt:
                _tel_event("sdc_detected", rank=r, step=step,
                           kind="state_mismatch", epoch=epoch)
        return verdict

    # -- tier 2 audit -----------------------------------------------------------

    def audit(self, step_fn, live_fp, step=None, peers_agree=None):
        """Shadow replay: re-run the retained pre-step snapshot through
        ``step_fn(state, inputs) -> new_state`` and fingerprint the
        result (host math — `fingerprint_host`).

        Classification:
        - replay != live  → ``"memory"``: the live state was mutated
          outside the computation (bit flip / corrupt HBM);
        - replay == live, peers disagree → ``"compute"``: the step
          deterministically produces a wrong answer (bad core);
        - replay == live, peers agree (or solo) → ``"clean"``.

        Emits a ``replay_audit`` event, plus a kind-refined
        ``sdc_detected`` when corruption is confirmed.  Returns
        ``{kind, replay_fp, live_fp, step}`` or None when nothing is
        retained for the step."""
        if peers_agree is None:
            v = self.last_verdict
            peers_agree = v is None or v["ok"] or \
                self.rank not in v.get("corrupt", ())
        if step is None:
            ret = self.retained()
            if ret is None:
                return None
            step, state, inputs = ret
        else:
            ret = self.retained(step)
            if ret is None:
                return None
            state, inputs = ret
        self.replays += 1
        new_state = step_fn(state, inputs) if inputs is not None \
            else step_fn(state)
        replay_fp = fingerprint_host(new_state)
        live = int(live_fp)
        if replay_fp != live:
            kind = "memory"
        elif not peers_agree:
            kind = "compute"
        else:
            kind = "clean"
        out = {"kind": kind, "replay_fp": fp_hex(replay_fp),
               "live_fp": fp_hex(live), "step": int(step)}
        _tel_event("replay_audit", rank=self.rank, step=int(step),
                   kind=kind, replay_fp=out["replay_fp"],
                   live_fp=out["live_fp"])
        if kind != "clean":
            _tel_event("sdc_detected", rank=self.rank, step=int(step),
                       kind=kind, epoch=self.epoch)
        return out

    # -- quarantine -------------------------------------------------------------

    def quarantine(self, gang, verdict=None):
        """Turn a mismatch verdict into the `resilience.RankFailure`
        the existing elastic recovery path consumes: the survivors call
        ``gang.recover(failure)``, which reshapes the mesh around the
        corrupt rank(s) and restores state from a buddy snapshot or the
        disk manifest; the quarantined rank sees the epoch move past it
        (GangEvicted) and `ElasticGang.join`s back with clean state.
        Returns None when the verdict names nobody (ok or tie)."""
        from . import resilience

        verdict = self.last_verdict if verdict is None else verdict
        if not verdict or not verdict.get("corrupt"):
            return None
        corrupt = sorted(verdict["corrupt"])
        for r in corrupt:
            _tel_event("rank_quarantined", rank=r,
                       step=verdict.get("step"), epoch=gang.epoch)
        return resilience.RankFailure(corrupt, gang.epoch)


# -- fault-injection hooks (docs/resilience.md) --------------------------------

def _flip_bit_f32(raw, bit=20):
    """Flip one mantissa bit of element 0 of a float32 jax array."""
    import jax.numpy as jnp
    from jax import lax

    flat = raw.ravel()
    word = lax.bitcast_convert_type(flat[0], jnp.uint32)
    flipped = lax.bitcast_convert_type(
        word ^ jnp.uint32(1 << bit), raw.dtype)
    return flat.at[0].set(flipped).reshape(raw.shape)


def bit_flip_host(arr, bit=20):
    """In-place single-bit flip of element 0 of a numpy array (the
    thread-gang tests' corruption primitive)."""
    import numpy as np

    a = np.ascontiguousarray(arr)
    size = a.dtype.itemsize
    view = a.view({8: np.uint64, 4: np.uint32,
                   2: np.uint16}.get(size, np.uint8)).ravel()
    view[0] ^= type(view[0])(1 << min(bit, size * 8 - 1))
    if a is not arr:
        arr.ravel()[0] = a.ravel()[0]
    return arr


def maybe_bit_flip_param(rank=None, params=()) -> bool:
    """``bit_flip_param:K``: flip one bit in the first trainable
    parameter of rank K, once — the live state diverges from its
    replicas and from its own replay (``kind="memory"``).  Consumes
    the rank's charge; returns True when it fired."""
    from . import resilience

    if rank is None:
        rank = self_rank()
    if not resilience.consume_rank_fault("bit_flip_param", rank):
        return False
    for p in params:
        raw = getattr(getattr(p, "data", lambda: p)(), "_data", None)
        if raw is None:
            import numpy as np

            arr = np.asarray(p)
            if arr.dtype.kind != "f" or arr.size == 0:
                continue
            bit_flip_host(p if hasattr(p, "dtype") else arr)
            return True
        import jax.numpy as jnp

        if not jnp.issubdtype(raw.dtype, jnp.floating) or raw.size == 0:
            continue
        p.data()._set_data(_flip_bit_f32(raw))
        return True
    return False


def maybe_bit_flip_grad(rank=None, grads=()) -> bool:
    """``bit_flip_grad:K``: flip one bit in rank K's first float
    gradient before the update (eager path — the captured program's
    gradients never materialize, so the Trainer routes the armed step
    to the oracle, the ``nan_grad`` discipline)."""
    from . import resilience

    if rank is None:
        rank = self_rank()
    if not grads or not resilience.consume_rank_fault("bit_flip_grad",
                                                      rank):
        return False
    import jax.numpy as jnp

    for g in grads:
        raw = getattr(g, "_data", None)
        if raw is None or not jnp.issubdtype(raw.dtype, jnp.floating) \
                or raw.size == 0:
            continue
        g._set_data(_flip_bit_f32(raw))
        return True
    return False


def maybe_bad_core(rank=None, value=None):
    """``bad_core:K``: rank K's compute is deterministically wrong —
    returns a perturbed copy of ``value`` (the step's input) once the
    charge fires, else ``value`` unchanged.  Perturbing the INPUT
    before it is recorded for replay is what makes the shadow replay
    reproduce the wrong answer (replay == live, peers disagree →
    ``kind="compute"``)."""
    from . import resilience

    if rank is None:
        rank = self_rank()
    if not resilience.consume_rank_fault("bad_core", rank):
        return value
    import numpy as np

    out = np.array(value, copy=True)
    flat = out.ravel()
    if flat.size and out.dtype.kind == "f":
        flat[0] = flat[0] * 1.0000001 + 1e-6
    return out if isinstance(value, np.ndarray) else type(value)(out)
