"""RecordIO: the reference's packed-record container format.

Reference parity: 3rdparty/dmlc-core RecordIO codec
(include/dmlc/recordio.h) + python/mxnet/recordio.py — MXRecordIO,
MXIndexedRecordIO, IRHeader pack/unpack, pack_img/unpack_img.

Byte-compatible with the reference format: each record is
``[kMagic:u32][cflag|length:u32][payload][pad to 4]`` with kMagic
0xced7230a; cflag (upper 3 bits) marks continuation splits when a record
contains the magic — identical framing, so ``.rec`` files pack with the
reference's im2rec are readable.

A C++ fast path (src/recordio.cc, loaded via ctypes) handles bulk reads;
this module is the reference implementation and fallback.

FORMAT NOTE (round 2): the continuation-split framing was corrected to
exact dmlc-core semantics (aligned-magic excision; reader re-inserts the
magic).  Files written by the round-1 codec whose records embedded the
magic are NOT readable by this codec (and were never reference-compatible
to begin with); re-pack them.
"""

from __future__ import annotations

import ctypes
import numbers
import os
import struct

import numpy as np

from . import resilience
from .base import MXNetError

_MAGIC = 0xced7230a
_LFLAG_BITS = 29
_LENGTH_MASK = (1 << _LFLAG_BITS) - 1


def _encode_lrec(cflag, length):
    return (cflag << _LFLAG_BITS) | length


def _decode_lrec(data):
    return data >> _LFLAG_BITS, data & _LENGTH_MASK


class MXRecordIO:
    """Sequential .rec reader/writer (reference: mx.recordio.MXRecordIO).

    ``skip_corrupt=True`` makes the reader tolerate corruption: a bad
    magic resyncs to the next aligned magic, a truncated tail reads as
    EOF, and an injected-corrupt record is skipped — each with a warning.
    The default is strict (raise MXNetError), matching the reference.
    """

    def __init__(self, uri, flag, skip_corrupt=False):
        self.uri = uri
        self.flag = flag
        self.skip_corrupt = skip_corrupt
        self.handle = None
        self.is_open = False
        self.open()

    def open(self, _reopen=False):
        if self.flag == "w":
            # a re-open (unpickle / fork reset) must NOT truncate what was
            # already written — append instead
            self.handle = resilience.io_retry(
                lambda: open(self.uri, "ab" if _reopen else "wb"),
                description=f"open {self.uri}")
            self.writable = True
        elif self.flag == "r":
            self.handle = resilience.io_retry(
                lambda: open(self.uri, "rb"),
                description=f"open {self.uri}")
            self.writable = False
        else:
            raise ValueError("Invalid flag %s" % self.flag)
        self.pid = os.getpid()
        self.is_open = True
        self._nread = 0

    def close(self):
        if not self.is_open:
            return
        self.handle.close()
        self.is_open = False
        self.pid = None

    def __del__(self):
        self.close()

    def __getstate__(self):
        """Override pickling behavior (DataLoader worker support)."""
        is_open = self.is_open
        self.close()
        d = dict(self.__dict__)
        d["is_open"] = is_open
        d.pop("handle", None)
        return d

    def __setstate__(self, d):
        self.__dict__ = d
        is_open = d.get("is_open", False)
        self.is_open = False
        self.handle = None
        if is_open:
            self.open(_reopen=True)

    def _check_pid(self, allow_reset=False):
        # forked workers must reopen to get their own file offset
        if self.pid != os.getpid():
            if allow_reset:
                self.reset()
            else:
                raise RuntimeError("Forbidden operation in multiple "
                                   "processes")

    def write(self, buf):
        """Write one record with reference framing (continuation-split on
        embedded magics).

        dmlc-core semantics (3rdparty/dmlc-core/src/recordio.cc
        RecordIOWriter::WriteRecord): scan only 4-byte-ALIGNED positions
        for the magic; each embedded aligned magic is EXCISED from the
        written payload and acts as the chunk delimiter (cflag 1=first,
        2=middle, 3=last chunk); the reader re-inserts kMagic before every
        cflag-2/3 chunk.  Unaligned embedded magics are left in place
        (harmless — framing is aligned).
        """
        assert self.writable
        self._check_pid()
        magic_bytes = struct.pack("<I", _MAGIC)
        splits = []
        idx = buf.find(magic_bytes)
        while idx != -1:
            if idx % 4 == 0:
                splits.append(idx)
                idx = buf.find(magic_bytes, idx + 4)
            else:
                idx = buf.find(magic_bytes, idx + 1)
        if not splits:
            self._write_chunk(0, buf)
            return
        begin = 0
        for n, i in enumerate(splits):
            self._write_chunk(1 if n == 0 else 2, buf[begin:i])
            begin = i + 4
        self._write_chunk(3, buf[begin:])

    def _write_chunk(self, cflag, data):
        # each chunk stores its OWN payload length (dmlc framing)
        self.handle.write(struct.pack("<II", _MAGIC,
                                      _encode_lrec(cflag, len(data))))
        self.handle.write(data)
        pad = (4 - len(data) % 4) % 4
        if pad:
            self.handle.write(b"\x00" * pad)

    def _corrupt(self, msg):
        """Corruption policy gate: strict raises; skip_corrupt warns and
        returns True so the caller can skip/resync."""
        if not self.skip_corrupt:
            raise MXNetError(msg)
        import warnings

        warnings.warn(f"RecordIO: {msg}; skipping (skip_corrupt=True)",
                      stacklevel=3)
        return True

    def _resync(self, magic_bytes):
        """Scan forward to the next 4-byte-ALIGNED magic (framing is
        aligned, so any real record header lands there); returns False at
        EOF.  Only reachable in skip_corrupt mode."""
        pos = self.handle.tell() - 4  # re-examine the 2nd header word
        pos += (-pos) % 4
        self.handle.seek(pos)
        while True:
            chunk_start = self.handle.tell()
            chunk = self.handle.read(1 << 16)
            if not chunk:
                return False
            i = chunk.find(magic_bytes)
            while i != -1:
                if (chunk_start + i) % 4 == 0:
                    self.handle.seek(chunk_start + i)
                    return True
                i = chunk.find(magic_bytes, i + 1)
            # a magic may straddle the chunk boundary
            self.handle.seek(chunk_start + max(1, len(chunk) - 3))

    def read(self):
        """Read one record; None at EOF.

        Re-inserts the excised kMagic before every continuation (cflag
        2/3) chunk — dmlc-core RecordIOReader::NextRecord semantics.

        Corruption detection: a bad magic, a partial trailing header, or
        a short payload read (truncated tail) hits the ``skip_corrupt``
        policy — strict raise by default, warn+skip/resync when enabled.
        The ``corrupt_record:K`` fault-injection site makes the K-th
        record of this reader read as corrupt (hermetic test hook).
        """
        assert not self.writable
        self._check_pid(allow_reset=True)
        out = None
        magic_bytes = struct.pack("<I", _MAGIC)
        while True:
            header = self.handle.read(8)
            if len(header) < 8:
                if len(header) == 0 and out is None:
                    return None  # clean EOF
                self._corrupt(
                    f"truncated RecordIO tail in {self.uri} "
                    f"({len(header)} trailing header bytes)")
                return None
            magic, lrec = struct.unpack("<II", header)
            if magic != _MAGIC:
                self._corrupt(
                    f"Invalid RecordIO magic in {self.uri} at offset "
                    f"{self.handle.tell() - 8}")
                out = None
                if self._resync(magic_bytes):
                    continue
                return None
            cflag, length = _decode_lrec(lrec)
            data = self.handle.read(length)
            if len(data) < length:
                self._corrupt(
                    f"truncated RecordIO record in {self.uri} (want "
                    f"{length} payload bytes, got {len(data)})")
                return None
            self._skip_pad(length)
            complete = None
            if cflag == 0:
                complete = data
            elif cflag == 1:
                out = data
                continue
            elif out is None:
                self._corrupt(
                    f"RecordIO continuation chunk without start in "
                    f"{self.uri}")
                continue  # skip mode: drop the orphan chunk, keep going
            else:
                out += magic_bytes + data
                if cflag != 3:
                    continue
                complete = out
                out = None
            idx = self._nread
            self._nread += 1
            if resilience.fault_arg("corrupt_record") == idx and \
                    resilience.consume_fault("corrupt_record"):
                self._corrupt(
                    f"injected corrupt record {idx} in {self.uri}")
                continue  # skip mode: drop the poisoned record
            return complete

    def _skip_pad(self, length):
        pad = (4 - length % 4) % 4
        if pad:
            self.handle.read(pad)

    def reset(self):
        self.close()
        self.open()

    def tell(self):
        return self.handle.tell()

    def seek(self, pos):
        assert not self.writable
        self._check_pid(allow_reset=True)
        self.handle.seek(pos)


class MXIndexedRecordIO(MXRecordIO):
    """Random-access .rec with .idx sidecar (reference:
    mx.recordio.MXIndexedRecordIO)."""

    def __init__(self, idx_path, uri, flag, key_type=int,
                 skip_corrupt=False):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        self.fidx = None
        super().__init__(uri, flag, skip_corrupt=skip_corrupt)

    def open(self, _reopen=False):
        super().open(_reopen=_reopen)
        self.idx = {}
        self.keys = []
        if self.flag == "r" and os.path.isfile(self.idx_path):
            self.fidx = resilience.io_retry(
                lambda: open(self.idx_path, "r"),
                description=f"open {self.idx_path}")
            for line in iter(self.fidx.readline, ""):
                line = line.strip().split("\t")
                key = self.key_type(line[0])
                self.idx[key] = int(line[1])
                self.keys.append(key)
        elif self.flag == "w":
            self.fidx = open(self.idx_path, "a" if _reopen else "w")

    def close(self):
        if not self.is_open:
            return
        super().close()
        if self.fidx is not None:
            self.fidx.close()
            self.fidx = None

    def __getstate__(self):
        d = super().__getstate__()
        d.pop("fidx", None)
        return d

    def seek(self, idx):
        assert not self.writable
        self._check_pid(allow_reset=True)
        pos = self.idx[idx]
        self.handle.seek(pos)

    def read_idx(self, idx):
        self.seek(idx)
        return self.read()

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.fidx.write(f"{key}\t{pos}\n")
        self.idx[key] = pos
        self.keys.append(key)


# -- image record header (reference: python/mxnet/recordio.py IRHeader) --------

_IR_FORMAT = "IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


class IRHeader:
    """flag, label, id, id2 (reference: IRHeader namedtuple)."""

    __slots__ = ("flag", "label", "id", "id2")

    def __init__(self, flag, label, id, id2):
        self.flag = flag
        self.label = label
        self.id = id
        self.id2 = id2

    def __iter__(self):
        return iter((self.flag, self.label, self.id, self.id2))

    def __eq__(self, other):
        return tuple(self) == tuple(other)


def pack(header, s):
    """Pack a header and byte payload into one record (reference: pack)."""
    header = IRHeader(*header)
    if isinstance(header.label, numbers.Number):
        header = IRHeader(0, header.label, header.id, header.id2)
    else:
        label = np.asarray(header.label, dtype=np.float32)
        header = IRHeader(label.size, 0, header.id, header.id2)
        s = label.tobytes() + s
    s = struct.pack(_IR_FORMAT, int(header.flag), float(header.label),
                    int(header.id), int(header.id2)) + s
    return s


def unpack(s):
    """Unpack a record into (IRHeader, payload)."""
    header = IRHeader(*struct.unpack(_IR_FORMAT, s[:_IR_SIZE]))
    s = s[_IR_SIZE:]
    if header.flag > 0:
        label = np.frombuffer(s[:header.flag * 4], dtype=np.float32)
        header = IRHeader(header.flag, label, header.id, header.id2)
        s = s[header.flag * 4:]
    return header, s


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    """Pack an image array (H,W,C uint8) via PIL encode (reference uses
    OpenCV)."""
    from .image import imencode

    return pack(header, imencode(img, quality=quality, img_fmt=img_fmt))


def unpack_img(s, iscolor=-1):
    """Unpack a record into (IRHeader, image ndarray)."""
    from .image import imdecode_np

    header, s = unpack(s)
    return header, imdecode_np(s, iscolor)
