"""Weight initializers.

Reference parity: python/mxnet/initializer.py — Initializer base with
registry + name-pattern dispatch (``_weight``/``_bias``/``_gamma``/...),
InitDesc, and the built-ins: Zero, One, Constant, Uniform, Normal,
Orthogonal, Xavier, MSRAPrelu, Bilinear, LSTMBias, Mixed, Load.

Randomness draws from numpy's global RNG (seeded by ``mx.random.seed``,
matching the reference's CPU-side initializer behavior) — initialization is
a one-time host-side event, so there is no reason to burn a TPU PRNG key.
"""

from __future__ import annotations

import json
import logging
import re

import numpy as _np

from .base import MXNetError, np_dtype
from .ndarray.ndarray import NDArray, _from_jax

_INIT_REGISTRY = {}


def register(klass):
    name = klass.__name__.lower()
    _INIT_REGISTRY[name] = klass
    return klass


def create(name, **kwargs):
    """mx.init.create — build an initializer from its registered name."""
    if isinstance(name, Initializer):
        return name
    if name.lower() not in _INIT_REGISTRY:
        raise ValueError(f"Cannot find initializer {name}")
    return _INIT_REGISTRY[name.lower()](**kwargs)


class InitDesc(str):
    """Name + attrs descriptor passed to initializers (reference:
    mx.init.InitDesc)."""

    def __new__(cls, name, attrs=None, global_init=None):
        ret = super().__new__(cls, name)
        ret.attrs = attrs or {}
        ret.global_init = global_init
        return ret


class Initializer:
    """Base initializer; callable on (InitDesc, NDArray)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs
        self._verbose = False
        self._print_func = None

    def set_verbosity(self, verbose=False, print_func=None):
        self._verbose = verbose
        if print_func is None:
            def asum_stat(x):
                return str((_np.abs(x.asnumpy())).mean())
            print_func = asum_stat
        self._print_func = print_func
        return self

    def _verbose_print(self, desc, init, arr):
        if self._verbose and self._print_func:
            logging.info("Initialized %s as %s: %s", desc, init,
                         self._print_func(arr))

    def dumps(self):
        """JSON [name, kwargs] — reference serialization for sending the
        initializer to KVStore servers."""
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, desc, arr):
        if not isinstance(desc, str):
            raise TypeError("desc must be an InitDesc or string")
        if isinstance(desc, InitDesc):
            if desc.global_init is None:
                desc.global_init = self
            # a per-parameter init (Parameter(init=...)) overrides suffix
            # dispatch (reference: attrs['__init__'] handling)
            init = desc.attrs.get("__init__", "")
            if init:
                create(init)._init_weight(desc, arr)
                self._verbose_print(desc, str(init), arr)
                return
        if desc.endswith("weight"):
            self._init_weight(desc, arr)
        elif desc.endswith("embed_table"):
            # ShardedEmbedding's table (mxnet_tpu/embedding): a weight
            # in every sense — named differently only so the row-shard
            # overlay can claim it without colliding with the
            # column-parallel ``embedding\d*_weight`` TP rule
            self._init_weight(desc, arr)
        elif desc.endswith("bias"):
            self._init_bias(desc, arr)
        elif desc.endswith("gamma"):
            self._init_gamma(desc, arr)
        elif desc.endswith("beta"):
            self._init_beta(desc, arr)
        elif desc.endswith("min"):
            self._init_zero(desc, arr)
        elif desc.endswith("max"):
            self._init_one(desc, arr)
        elif desc.endswith("weight_quantize"):
            self._init_quantized_weight(desc, arr)
        elif desc.endswith("bias_quantize"):
            self._init_quantized_bias(desc, arr)
        else:
            self._init_default(desc, arr)
        self._verbose_print(desc, "init", arr)

    # legacy call signature: init(name, arr)
    def _legacy_init(self, name, arr):
        self.__call__(InitDesc(name), arr)

    def _set(self, arr, value):
        import jax.numpy as jnp

        arr._set_data(jnp.asarray(_np.asarray(value),
                                  dtype=arr._data.dtype))

    def _init_bilinear(self, _, arr):
        shape = arr.shape
        weight = _np.zeros(int(_np.prod(shape)), dtype="float32")
        f = _np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(_np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        self._set(arr, weight.reshape(shape))

    def _init_loc_bias(self, _, arr):
        assert arr.shape[0] == 6
        self._set(arr, _np.array([1.0, 0, 0, 0, 1.0, 0]))

    def _init_zero(self, _, arr):
        self._set(arr, _np.zeros(arr.shape))

    def _init_one(self, _, arr):
        self._set(arr, _np.ones(arr.shape))

    def _init_bias(self, _, arr):
        self._set(arr, _np.zeros(arr.shape))

    def _init_quantized_bias(self, _, arr):
        self._set(arr, _np.zeros(arr.shape))

    def _init_gamma(self, _, arr):
        self._set(arr, _np.ones(arr.shape))

    def _init_beta(self, _, arr):
        self._set(arr, _np.zeros(arr.shape))

    def _init_weight(self, name, arr):
        raise NotImplementedError("Must override it")

    def _init_quantized_weight(self, _, arr):
        self._set(arr, _np.random.randint(-127, 127, arr.shape))

    def _init_default(self, name, _):
        raise ValueError(
            f"Unknown initialization pattern for {name}. Default "
            "initialization is now limited to \"weight\", \"bias\", "
            "\"gamma\" (1.0), and \"beta\" (0.0). Please use "
            "mx.sym.Variable(init=mx.init.*) to set initialization "
            "pattern")

    def __eq__(self, other):
        if not isinstance(other, Initializer):
            return NotImplemented
        return (self.__class__ is other.__class__
                and self._kwargs == other._kwargs)

    __hash__ = None


@register
class Zero(Initializer):
    def __init__(self):
        super().__init__()

    def _init_weight(self, _, arr):
        self._set(arr, _np.zeros(arr.shape))


@register
class One(Initializer):
    def __init__(self):
        super().__init__()

    def _init_weight(self, _, arr):
        self._set(arr, _np.ones(arr.shape))


# reference alias names (mx.init registry: @register(alias=...))
_INIT_REGISTRY["zeros"] = Zero
_INIT_REGISTRY["ones"] = One


@register
class Constant(Initializer):
    def __init__(self, value):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, _, arr):
        v = self.value
        if isinstance(v, NDArray):
            v = v.asnumpy()
        self._set(arr, _np.broadcast_to(v, arr.shape))


@register
class Uniform(Initializer):
    """U(-scale, scale) (reference default scale 0.07)."""

    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, _, arr):
        self._set(arr, _np.random.uniform(-self.scale, self.scale,
                                          arr.shape))


@register
class Normal(Initializer):
    """N(0, sigma) (reference default sigma 0.01)."""

    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, _, arr):
        self._set(arr, _np.random.normal(0, self.sigma, arr.shape))


@register
class Orthogonal(Initializer):
    """Orthogonal matrix init (Saxe et al.; reference: mx.init.Orthogonal)."""

    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, _, arr):
        nout = arr.shape[0]
        nin = int(_np.prod(arr.shape[1:]))
        if self.rand_type == "uniform":
            tmp = _np.random.uniform(-1.0, 1.0, (nout, nin))
        else:
            tmp = _np.random.normal(0.0, 1.0, (nout, nin))
        u, _v, q = _np.linalg.svd(tmp, full_matrices=False)
        res = u if u.shape == tmp.shape else q
        self._set(arr, self.scale * res.reshape(arr.shape))


@register
class Xavier(Initializer):
    """Xavier/Glorot init (reference: mx.init.Xavier).

    factor_type in {'avg','in','out'}; rnd_type in {'uniform','gaussian'}.
    """

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, name, arr):
        shape = arr.shape
        hw_scale = 1.0
        if len(shape) < 2:
            raise ValueError(
                f"Xavier initializer cannot be applied to vector {name}. "
                "It requires at least 2D.")
        if len(shape) > 2:
            hw_scale = _np.prod(shape[2:])
        fan_in = shape[1] * hw_scale
        fan_out = shape[0] * hw_scale
        if self.factor_type == "avg":
            factor = (fan_in + fan_out) / 2.0
        elif self.factor_type == "in":
            factor = fan_in
        elif self.factor_type == "out":
            factor = fan_out
        else:
            raise ValueError("Incorrect factor type")
        scale = _np.sqrt(self.magnitude / factor)
        if self.rnd_type == "uniform":
            self._set(arr, _np.random.uniform(-scale, scale, shape))
        elif self.rnd_type == "gaussian":
            self._set(arr, _np.random.normal(0, scale, shape))
        else:
            raise ValueError("Unknown random type")


@register
class MSRAPrelu(Xavier):
    """He/MSRA init for PReLU nets (reference: mx.init.MSRAPrelu)."""

    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Bilinear(Initializer):
    def __init__(self):
        super().__init__()

    def _init_weight(self, _, arr):
        self._init_bilinear(_, arr)


@register
class LSTMBias(Initializer):
    """Initializes LSTM biases to 0 except the forget gate (reference:
    mx.init.LSTMBias)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, name, arr):
        bias = _np.zeros(arr.shape)
        num_hidden = int(arr.shape[0] / 4)
        bias[num_hidden:2 * num_hidden] = self.forget_bias
        self._set(arr, bias)

    _init_bias = _init_weight


@register
class FusedRNN(Initializer):
    """Initializer for fused RNN packed parameters (reference:
    mx.init.FusedRNN) — delegates per-slice to the wrapped initializer."""

    def __init__(self, init, num_hidden, num_layers, mode,
                 bidirectional=False, forget_bias=1.0):
        if isinstance(init, str):
            klass, kwargs = json.loads(init)
            init = _INIT_REGISTRY[klass.lower()](**kwargs)
        super().__init__(init=init.dumps() if init is not None else None,
                         num_hidden=num_hidden, num_layers=num_layers,
                         mode=mode, bidirectional=bidirectional,
                         forget_bias=forget_bias)
        self._init = init
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._forget_bias = forget_bias

    def _init_weight(self, desc, arr):
        # packed single-vector parameter: init as a whole via the wrapped
        # initializer, then overwrite LSTM forget-gate biases.  Packing
        # (ops/rnn.py): all (Wx, Wh) pairs layer/direction-major, then all
        # (bx, bh) pairs; LSTM gate order i f g o → forget slice [H, 2H).
        if self._init is not None:
            self._init._init_weight(desc, arr)
        if self._mode != "lstm":
            return
        a = arr.asnumpy().copy()
        h = self._num_hidden
        dirs = 2 if self._bidirectional else 1
        gates = 4
        bias_start = a.size - self._num_layers * dirs * 2 * gates * h
        off = bias_start
        for _layer in range(self._num_layers):
            for _d in range(dirs):
                a[off + h:off + 2 * h] = self._forget_bias  # bx forget
                off += gates * h
                a[off + h:off + 2 * h] = 0.0                # bh forget
                off += gates * h
        self._set(arr, a)


class Mixed:
    """Patterns → initializers dispatch (reference: mx.init.Mixed)."""

    def __init__(self, patterns, initializers):
        assert len(patterns) == len(initializers)
        self.map = list(zip([re.compile(p) for p in patterns], initializers))

    def __call__(self, name, arr):
        for prog, init in self.map:
            if prog.match(name):
                init(name, arr)
                return
        raise ValueError(
            f"Parameter name {name} did not match any pattern. Consider "
            "adding a \".*\" pattern at the and with default Initializer.")


@register
class Load:
    """Init from a dict of arrays, falling back to default_init
    (reference: mx.init.Load)."""

    def __init__(self, param, default_init=None, verbose=False):
        if isinstance(param, str):
            from .ndarray import load as nd_load

            param = nd_load(param)
        self.param = {}
        for name, arr in param.items():
            if name.startswith("arg:") or name.startswith("aux:"):
                self.param[name[4:]] = arr
            else:
                self.param[name] = arr
        self.default_init = default_init
        self.verbose = verbose

    def __call__(self, name, arr):
        if name in self.param:
            if arr.shape != self.param[name].shape:
                raise ValueError(
                    f"Parameter {name} cannot be initialized from loading. "
                    f"Shape mismatch, target {arr.shape} vs loaded "
                    f"{self.param[name].shape}")
            arr._set_data(self.param[name]._data)
            if self.verbose:
                logging.info("Initialized %s by loading", name)
        else:
            if self.default_init is None:
                raise ValueError(
                    f"Cannot Initialize parameter: {name}. Not found in "
                    "loaded param and no default Initializer is provided.")
            self.default_init(name, arr)
            if self.verbose:
                logging.info("Initialized %s by default", name)
