"""Profiler.

Reference parity: src/profiler/profiler.cc + python/mxnet/profiler.py —
set_config / set_state('run'|'stop') / pause / resume / dump /
aggregate stats, chrome://tracing JSON output, env autostart
(MXNET_PROFILER_AUTOSTART).

TPU-first: the host-side tracer records per-op dispatch spans from the
NDArray invoke layer (the analog of ThreadedEngine::ExecuteOprBlock hooks);
device-side time belongs to XLA's own profiler — ``start_xla_trace`` /
``stop_xla_trace`` wrap ``jax.profiler`` so one call captures an xplane
trace alongside the chrome dump (open either in Perfetto).  With
``profile_sync=True`` every traced op blocks on completion, so spans are
true op latencies (NaiveEngine-style measurement).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import defaultdict

from . import telemetry as _telemetry

_LOCK = threading.Lock()


class _State:
    running = False
    sync = False
    filename = "profile.json"
    events: list = []
    aggregate: dict = defaultdict(lambda: [0, 0.0, float("inf"), 0.0])
    xla_dir = None


_S = _State()


def is_running() -> bool:
    return _S.running


def set_config(profile_all=False, profile_symbolic=False,
               profile_imperative=False, profile_memory=False,
               profile_api=False, filename="profile.json",
               continuous_dump=False, profile_sync=False, **kwargs):
    """Reference: mx.profiler.set_config (MXSetProcessProfilerConfig)."""
    _S.filename = filename
    _S.sync = profile_sync


def set_state(state="stop", profile_process="worker"):
    """'run' starts collection; 'stop' ends it (reference:
    MXSetProcessProfilerState)."""
    if state == "run":
        _S.running = True
    elif state == "stop":
        _S.running = False
    else:
        raise ValueError("state must be 'run' or 'stop'")


def pause(profile_process="worker"):
    _S.running = False


def resume(profile_process="worker"):
    _S.running = True


def record_span(name, category, t_start, t_end):
    """Called from the dispatch layer for every op while running."""
    with _LOCK:
        _S.events.append({
            "name": name, "cat": category, "ph": "X",
            "ts": t_start * 1e6, "dur": (t_end - t_start) * 1e6,
            "pid": os.getpid(), "tid": threading.get_ident()})
        agg = _S.aggregate[name]
        agg[0] += 1
        dur = (t_end - t_start) * 1e3
        agg[1] += dur
        agg[2] = min(agg[2], dur)
        agg[3] = max(agg[3], dur)


class _OpSpan:
    """Context manager used by the invoke layer."""

    __slots__ = ("name", "t0")

    def __init__(self, name):
        self.name = name

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        record_span(self.name, "operator", self.t0, time.perf_counter())


def op_span(name):
    return _OpSpan(name)


def want_sync() -> bool:
    return _S.running and _S.sync


def dumps(reset=False):
    """Chrome-trace JSON string (reference: MXDumpProfile)."""
    with _LOCK:
        out = json.dumps({"traceEvents": list(_S.events),
                          "displayTimeUnit": "ms"})
        if reset:
            _S.events.clear()
    return out


def dump(finished=True, profile_process="worker"):
    with open(_S.filename, "w") as f:
        f.write(dumps())


def get_summary(reset=False):
    """Aggregate per-op stats table (reference:
    MXAggregateProfileStatsPrint)."""
    with _LOCK:
        lines = [f"{'Name':<40}{'Count':>8}{'Total(ms)':>12}"
                 f"{'Min(ms)':>10}{'Max(ms)':>10}{'Avg(ms)':>10}"]
        for name, (count, total, mn, mx) in sorted(
                _S.aggregate.items(), key=lambda kv: -kv[1][1]):
            lines.append(f"{name:<40}{count:>8}{total:>12.3f}{mn:>10.3f}"
                         f"{mx:>10.3f}{total / count:>10.3f}")
        if reset:
            _S.aggregate.clear()
    return "\n".join(lines)


def aggregates(reset=False):
    """Structured counterpart of `get_summary`: ``{name: {count,
    total_ms, min_ms, max_ms}}`` — bench.py derives its step-time
    breakdown (data stall / host prep / dispatch / collective /
    readback shares) from the named `annotate` scopes collected here."""
    with _LOCK:
        out = {name: {"count": count, "total_ms": total,
                      "min_ms": mn, "max_ms": mx}
               for name, (count, total, mn, mx) in _S.aggregate.items()}
        if reset:
            _S.aggregate.clear()
    return out


dump_profile = dump
profiler_set_config = set_config
profiler_set_state = set_state


# -- XLA device-side tracing (xplane) ------------------------------------------

def start_xla_trace(log_dir="/tmp/mxnet_tpu_xla_trace"):
    """Capture an XLA xplane trace (view in xprof/Perfetto/TensorBoard)."""
    import jax

    _S.xla_dir = log_dir
    jax.profiler.start_trace(log_dir)
    return log_dir


def stop_xla_trace():
    import jax

    jax.profiler.stop_trace()
    out, _S.xla_dir = _S.xla_dir, None
    return out


def annotate(name):
    """Named phase marker for hot-path stages ("allreduce",
    "optimizer_update", "bucket_pack", ...): a `jax.profiler.
    TraceAnnotation` so the stage shows up named in xplane traces, plus a
    host span when the host profiler is running."""
    return scope(name)


class scope:
    """Annotation scope appearing in both host + XLA traces (reference:
    profiler scopes / NVTX ranges).

    The `jax.profiler.TraceAnnotation` is constructed ONLY while a trace
    can actually see it — the host profiler running, or an XLA trace
    opened via `start_xla_trace` — so hot-path `annotate` calls with
    profiling off pay two `perf_counter` reads, not a context-manager
    round-trip into jax.  The host duration is always measured and
    forwarded to the telemetry step assembler (mxnet_tpu/telemetry.py),
    which is how StepStats gets its breakdown without the profiler on.
    """

    __slots__ = ("name", "_jax", "_t0")

    def __init__(self, name):
        self.name = name

    def __enter__(self):
        if _S.running or _S.xla_dir is not None:
            import jax

            self._jax = jax.profiler.TraceAnnotation(self.name)
            self._jax.__enter__()
        else:
            self._jax = None
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        if self._jax is not None:
            self._jax.__exit__(*exc)
        if _S.running:
            record_span(self.name, "scope", self._t0, t1)
        _telemetry.on_scope(self.name, t1 - self._t0)


if os.environ.get("MXNET_PROFILER_AUTOSTART", "0") == "1":
    set_state("run")
