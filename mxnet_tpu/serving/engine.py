"""AOT bucketed serving engine for the decoder-only model zoo.

The training side captures the whole step as ONE donated jit program
(gluon/captured.py); this module applies the same discipline to the
request path.  Three properties, all pinned by tests/test_serving.py:

- **Zero retraces after warmup.**  Every (batch bucket × seq bucket)
  pair gets ONE ahead-of-time program via the same
  ``jit(...).lower(*avals).compile()`` path ``CapturedStep`` uses for
  its cost analysis; requests are padded to the nearest bucket and run
  through the pre-compiled executable directly — the jit tracing
  machinery is never re-entered on the request path.  A module-level
  trace counter (incremented as a Python side effect inside the traced
  function, so it ticks exactly once per compile) makes the pin
  checkable: ``trace_count()`` must not move after ``warmup()``.
- **KV-cache decode.**  The per-layer key/value cache is laid out
  stage-major — ``(L, B, H, W, Dh)`` with L the scanned-trunk layer
  axis, matching the ``*_stack_*`` weight stacks
  (parallel/sharding.py TRANSFORMER_TP_RULES) — and donated between
  steps, so decode re-uses the prefill buffers in place.  Prefill
  (S = seq bucket) and decode (S = 1) are separate bucketed programs
  of the SAME traced function.
- **Hot reload without recompile.**  Weights are *arguments* to the
  compiled programs, not closed-over constants: swapping in new
  weights (from a live model or an AsyncCheckpointer state dict) is an
  array replacement under a lock — no retrace, no dropped requests
  (serving/replica.py swaps between batches).

Unlike ``gpt.CachedDecoder`` (one uniform-length batch, scalar write
position), the step here takes a **per-row position vector**, so a
coalesced batch can mix prompt lengths: each row's cache writes land at
its own offset (vmapped dynamic_update_slice) and its own causal mask.
Every op is row-independent (per-row LN / softmax / einsum rows), which
is what makes a coalesced batch bitwise equal to the same requests
served one-by-one through the same batch bucket — pad rows can never
leak into real rows.

Tensor-parallel serving (``mesh=``): weight stacks are head-/hidden-
reshaped and placed with NamedShardings following the Megatron
column/row split of TRANSFORMER_TP_RULES; the cache shards on its head
axis (parallel/sharding.serving_cache_sharding).
"""

from __future__ import annotations

import os
import threading
import time

from ..base import MXNetError
from ..gluon.model_zoo.gpt import (STACK_NAMES, _sample,
                                   extract_decoder_stacks)

# -- counters (the retrace-free pin) -------------------------------------------

_LOCK = threading.Lock()
_TRACE_COUNT = 0      # ticks inside the traced fn: once per (re)trace
_COMPILE_COUNT = 0    # lower().compile() calls
_DISPATCH_COUNT = 0   # compiled-program invocations


def _mark_trace():
    global _TRACE_COUNT
    with _LOCK:
        _TRACE_COUNT += 1


def trace_count():
    return _TRACE_COUNT


def compile_count():
    return _COMPILE_COUNT


def dispatch_count():
    return _DISPATCH_COUNT


def reset_counters():
    global _TRACE_COUNT, _COMPILE_COUNT, _DISPATCH_COUNT
    with _LOCK:
        _TRACE_COUNT = _COMPILE_COUNT = _DISPATCH_COUNT = 0


# -- bucket policy -------------------------------------------------------------

def batch_buckets_from_env(default=(1, 2, 4, 8)):
    """MXTPU_SERVE_BUCKETS: comma-separated ascending batch buckets."""
    raw = os.environ.get("MXTPU_SERVE_BUCKETS")
    if not raw:
        return tuple(default)
    try:
        buckets = tuple(sorted({int(x) for x in raw.split(",") if x}))
    except ValueError:
        return tuple(default)
    return buckets or tuple(default)


def prefill_buckets_for(window, floor=8):
    """Power-of-two prefill sequence buckets up to the cache window —
    log2(W) programs cover every prompt length (the same policy
    CachedDecoder.decode uses for its chunked prefill)."""
    buckets, s = [], max(1, floor)
    while s < window:
        buckets.append(s)
        s *= 2
    buckets.append(window)
    return tuple(buckets)


def state_for_serving(model):
    """Flat host state dict ``{param_name: np.ndarray}`` — the serving
    checkpoint convention AsyncCheckpointer saves and
    ``ServingEngine.reload_from_state`` consumes."""
    import numpy as np

    return {name: np.asarray(p.data()._data)
            for name, p in model.collect_params().items()}


def _stacks_from_state(state):
    """Rebuild (stacks, lnf, tok, pos) from a flat name→array state dict
    (scanned-trunk convention: scan_layers=True param names)."""
    import jax.numpy as jnp

    def get1(suffix):
        ks = [k for k in state if k.endswith(suffix)]
        if len(ks) != 1:
            raise MXNetError(
                f"serving reload: expected exactly one param ending "
                f"{suffix!r} in the checkpoint state, found {ks}")
        return jnp.asarray(state[ks[0]])

    if not any(k.endswith("qkv_stack_weight") for k in state):
        raise MXNetError(
            "serving reload: checkpoint state lacks the scanned-trunk "
            "(*_stack_*) parameter convention; save the model with "
            "scan_layers=True (serving.state_for_serving) or reload "
            "from a live model via reload_from_model")
    stacks = {nm: get1(nm) for nm in STACK_NAMES}
    return (stacks, (get1("lnf_gamma"), get1("lnf_beta")),
            get1("tok_embed_weight"), get1("pos_embed_weight"))


class ServingEngine:
    """Bucketed AOT prefill/decode over a GPTModel's weight stacks.

    ``serve_group(prompts, max_new_tokens)`` is the whole request path:
    pad to the nearest (batch, seq) bucket, one prefill dispatch, one
    decode dispatch per generated token, greedy (or temperature)
    sampling on host — every dispatch hits a pre-compiled program.
    """

    def __init__(self, model, batch_buckets=None, prefill_floor=8,
                 mesh=None, tp_axis="tp", dtype=None):
        self._W = model._max_length
        self._mesh = mesh
        self._tp_axis = tp_axis
        self._dtype = dtype
        self.batch_buckets = tuple(sorted(
            batch_buckets if batch_buckets is not None
            else batch_buckets_from_env()))
        self.prefill_buckets = prefill_buckets_for(self._W,
                                                   floor=prefill_floor)
        (stacks, lnf, tok, pos, num_heads,
         act) = extract_decoder_stacks(model)
        self._H = num_heads
        self._act = act
        self._C = int(tok.shape[1])
        self._L = int(stacks["qkv_stack_weight"].shape[0])
        self._vocab = int(tok.shape[0])
        if mesh is not None:
            n_tp = mesh.shape[tp_axis]
            F = int(stacks["ffn1_stack_weight"].shape[1])
            if num_heads % n_tp or F % n_tp:
                raise MXNetError(
                    f"ServingEngine: tp axis size {n_tp} must divide "
                    f"num_heads={num_heads} and ffn hidden={F}")
        self._reload_lock = threading.Lock()
        self.generation = 0
        self._weights = self._prepare_weights(stacks, lnf, tok, pos)
        self._programs = {}
        self._step = self._make_step()

    # -- weight plumbing -------------------------------------------------------

    def _shard(self, arr, spec):
        if self._mesh is None:
            return arr
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        return jax.device_put(arr,
                              NamedSharding(self._mesh, P(*spec)))

    def _prepare_weights(self, stacks, lnf, tok, pos):
        """Head-/hidden-major restructure + serving dtype + tp placement
        (the same Megatron column/row layout CachedDecoder._build
        derives, but produced as a flat argument tuple so the compiled
        programs take weights as inputs — the hot-reload contract)."""
        s = dict(stacks)
        if self._dtype is not None:
            for nm in ("qkv_stack_weight", "proj_stack_weight",
                       "ffn1_stack_weight", "ffn2_stack_weight"):
                s[nm] = s[nm].astype(self._dtype)
            tok = tok.astype(self._dtype)
            pos = pos.astype(self._dtype)
        L, H, C = self._L, self._H, self._C
        Dh = C // H
        tp = self._tp_axis
        qkvw = self._shard(s["qkv_stack_weight"].reshape(L, 3, H, Dh, C),
                           (None, None, tp))
        qkvb = self._shard(s["qkv_stack_bias"].reshape(L, 3, H, Dh),
                           (None, None, tp))
        pwh = self._shard(s["proj_stack_weight"].reshape(L, C, H, Dh),
                          (None, None, tp))
        f1w = self._shard(s["ffn1_stack_weight"], (None, tp))
        f1b = self._shard(s["ffn1_stack_bias"], (None, tp))
        f2w = self._shard(s["ffn2_stack_weight"], (None, None, tp))
        rep = ()
        return (self._shard(tok, rep), self._shard(pos, rep),
                qkvw, qkvb, pwh, self._shard(s["proj_stack_bias"], rep),
                f1w, f1b, f2w, self._shard(s["ffn2_stack_bias"], rep),
                self._shard(s["ln1_stack_gamma"], rep),
                self._shard(s["ln1_stack_beta"], rep),
                self._shard(s["ln2_stack_gamma"], rep),
                self._shard(s["ln2_stack_beta"], rep),
                self._shard(lnf[0], rep), self._shard(lnf[1], rep))

    def reload_from_model(self, model, step=None):
        """Swap in a live model's weights (shapes must match)."""
        stacks, lnf, tok, pos, H, act = extract_decoder_stacks(model)
        if H != self._H or act != self._act:
            raise MXNetError(
                f"serving reload: incompatible model "
                f"(heads {H} vs {self._H}, act {act!r} vs {self._act!r})")
        self._swap(stacks, lnf, tok, pos, step=step)

    def reload_from_state(self, state, step=None, expect_fp=None):
        """Swap in weights from an AsyncCheckpointer state dict
        (``state_for_serving`` convention).

        ``expect_fp``: optional integrity fingerprint (u64, the
        training side's attested `integrity.fingerprint_host` of this
        state).  When given, the state is re-fingerprinted here and a
        mismatch REJECTS the reload (``serving_reload_rejected``)
        instead of serving corrupt weights — end-to-end coverage of
        the restore path itself, past the per-shard CRCs."""
        if expect_fp is not None:
            from .. import integrity, telemetry

            got = integrity.fingerprint_host(state)
            if got != int(expect_fp):
                telemetry.event(
                    "serving_reload_rejected", step=step,
                    reason=f"state fingerprint {integrity.fp_hex(got)} "
                           f"!= attested "
                           f"{integrity.fp_hex(int(expect_fp))}")
                raise MXNetError(
                    "serving reload: restored state fingerprint does "
                    "not match the attested fingerprint — refusing to "
                    "serve corrupt weights")
        stacks, lnf, tok, pos = _stacks_from_state(state)
        self._swap(stacks, lnf, tok, pos, step=step)

    def _swap(self, stacks, lnf, tok, pos, step=None):
        from .. import telemetry

        got = tuple(stacks["qkv_stack_weight"].shape)
        want = (self._L, 3 * self._C, self._C)
        if got != want:
            raise MXNetError(
                f"serving reload: weight mismatch — qkv stack {got} vs "
                f"compiled {want}; a mismatched swap would force a "
                f"retrace on the request path")
        new_w = self._prepare_weights(stacks, lnf, tok, pos)
        for old, new in zip(self._weights, new_w):
            if tuple(old.shape) != tuple(new.shape) \
                    or old.dtype != new.dtype:
                raise MXNetError(
                    f"serving reload: weight mismatch "
                    f"{tuple(new.shape)}/{new.dtype} vs compiled "
                    f"{tuple(old.shape)}/{old.dtype} — a mismatched "
                    f"swap would force a retrace on the request path")
        with self._reload_lock:
            self._weights = new_w
            self.generation += 1
            gen = self.generation
        telemetry.event("serving_reload", generation=gen, step=step)

    # -- cache -----------------------------------------------------------------

    def _cache_sharding(self):
        from ..parallel.sharding import serving_cache_sharding

        return serving_cache_sharding(self._mesh, tp_axis=self._tp_axis)

    def init_cache(self, B):
        """Fresh zeroed (ck, cv) for batch bucket B: stage-major
        (L, B, H, W, Dh), serving dtype, head-sharded under tp."""
        import jax
        import jax.numpy as jnp

        tok = self._weights[0]
        shape = (self._L, B, self._H, self._W, self._C // self._H)
        ck = jnp.zeros(shape, tok.dtype)
        cv = jnp.zeros(shape, tok.dtype)
        if self._mesh is not None:
            ns = self._cache_sharding()
            ck = jax.device_put(ck, ns)
            cv = jax.device_put(cv, ns)
        return ck, cv

    # -- the traced block step -------------------------------------------------

    def _make_step(self):
        import jax
        import jax.numpy as jnp
        from jax import lax

        from ..ops.nn import layer_norm

        H, W = self._H, self._W
        Dh = self._C // H
        act = self._act
        mesh = self._mesh
        cache_ns = self._cache_sharding() if mesh is not None else None

        def step(w, ck, cv, pos, toks):
            """ck/cv (L, B, H, W, Dh) donated; pos (B,) per-row write
            offsets; toks (B, S) int32.  Returns (ck', cv', logits
            (B, S, vocab)).  S = seq bucket for prefill, 1 for decode."""
            _mark_trace()
            (tok_e, pos_e, qkvw, qkvb, pwh, pb, f1w, f1b, f2w, f2b,
             g1s, b1s, g2s, b2s, lnf_g, lnf_b) = w
            S = toks.shape[1]
            positions = pos[:, None] + jnp.arange(S)[None, :]  # (B, S)
            x = (jnp.take(tok_e, toks, axis=0) +
                 jnp.take(pos_e, positions, axis=0)
                 ).astype(jnp.float32)                         # (B, S, C)

            def layer(x, per):
                (qw, qb, pw, pb_l, f1w_l, f1b_l, f2w_l, f2b_l,
                 g1, b1, g2, b2, ck_l, cv_l) = per
                h = layer_norm(x, g1, b1)
                qkv = jnp.einsum("bsc,thdc->bsthd", h, qw) + qb
                qh = qkv[:, :, 0].swapaxes(1, 2)     # (B, H, S, Dh)
                kh = qkv[:, :, 1].swapaxes(1, 2)
                vh = qkv[:, :, 2].swapaxes(1, 2)

                def write(c, k, p):
                    # per-row cache write at that row's own offset
                    return lax.dynamic_update_slice(c, k, (0, p, 0))

                ck_l = jax.vmap(write)(ck_l, kh.astype(ck_l.dtype), pos)
                cv_l = jax.vmap(write)(cv_l, vh.astype(cv_l.dtype), pos)
                scores = jnp.einsum("bhsd,bhwd->bhsw", qh, ck_l) \
                    * (Dh ** -0.5)
                # per-row causal mask: row b at block offset s may see
                # cache slots <= pos[b] + s (stale pad garbage beyond is
                # invisible — the overwrite-before-attend invariant)
                mask = jnp.arange(W)[None, None, :] <= \
                    (pos[:, None, None] +
                     jnp.arange(S)[None, :, None])             # (B, S, W)
                scores = jnp.where(mask[:, None], scores, -1e30)
                p = jax.nn.softmax(scores, axis=-1)
                attn = jnp.einsum("bhsw,bhwd->bhsd", p, cv_l)
                attn = jnp.einsum("bhsd,chd->bsc", attn, pw) + pb_l
                x = x + attn
                h = layer_norm(x, g2, b2)
                h = h @ f1w_l.T + f1b_l
                h = jax.nn.gelu(h) if act == "gelu" \
                    else jnp.maximum(h, 0)
                x = x + (h @ f2w_l.T + f2b_l)
                return x, (ck_l, cv_l)

            per_layer = (qkvw, qkvb, pwh, pb, f1w, f1b, f2w, f2b,
                         g1s, b1s, g2s, b2s, ck, cv)
            x, (ck2, cv2) = lax.scan(layer, x, per_layer)
            h = layer_norm(x, lnf_g, lnf_b)
            logits = h @ tok_e.T
            if cache_ns is not None:
                # pin the donated buffers' output layout to the input
                # layout, so the next AOT call sees identical shardings
                ck2 = lax.with_sharding_constraint(ck2, cache_ns)
                cv2 = lax.with_sharding_constraint(cv2, cache_ns)
            return ck2, cv2, logits

        return step

    # -- AOT compilation -------------------------------------------------------

    def _aval(self, arr):
        import jax

        if self._mesh is None:
            return jax.ShapeDtypeStruct(tuple(arr.shape), arr.dtype)
        return jax.ShapeDtypeStruct(tuple(arr.shape), arr.dtype,
                                    sharding=arr.sharding)

    def _int_aval(self, shape):
        import jax
        import numpy as np

        if self._mesh is None:
            return jax.ShapeDtypeStruct(shape, np.int32)
        from jax.sharding import NamedSharding, PartitionSpec as P

        return jax.ShapeDtypeStruct(
            shape, np.int32, sharding=NamedSharding(self._mesh, P()))

    def _compile(self, B, S):
        """One donated program for bucket (B, S) via the captured-step
        AOT path (``lower(*avals).compile()`` — gluon/captured.py's
        ``_compiled_for_stats`` discipline applied to the request path)."""
        global _COMPILE_COUNT
        import jax

        w_avals = tuple(self._aval(x) for x in self._weights)
        ck, cv = self.init_cache(B)
        jfn = jax.jit(self._step, donate_argnums=(1, 2))
        compiled = jfn.lower(w_avals, self._aval(ck), self._aval(cv),
                             self._int_aval((B,)),
                             self._int_aval((B, S))).compile()
        with _LOCK:
            _COMPILE_COUNT += 1
        self._programs[(B, S)] = compiled
        return compiled

    def warmup(self):
        """Pre-compile every (batch × prefill) program plus the S=1
        decode program per batch bucket; afterwards the request path is
        retrace-free (``trace_count()`` is pinned)."""
        t0 = time.perf_counter()
        for B in self.batch_buckets:
            for S in self.prefill_buckets + (1,):
                if (B, S) not in self._programs:
                    self._compile(B, S)
        from .. import telemetry

        telemetry.event(
            "serving_warmup", programs=len(self._programs),
            compile_ms=round((time.perf_counter() - t0) * 1e3, 1))
        return self

    def program_count(self):
        return len(self._programs)

    def _call(self, B, S, ck, cv, pos, toks):
        global _DISPATCH_COUNT
        import jax
        import jax.numpy as jnp

        compiled = self._programs.get((B, S))
        if compiled is None:
            compiled = self._compile(B, S)
        pos = jnp.asarray(pos, jnp.int32)
        toks = jnp.asarray(toks, jnp.int32)
        if self._mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            rep = NamedSharding(self._mesh, P())
            pos = jax.device_put(pos, rep)
            toks = jax.device_put(toks, rep)
        with _LOCK:
            _DISPATCH_COUNT += 1
        with self._reload_lock:
            w = self._weights
        return compiled(w, ck, cv, pos, toks)

    # -- request path ----------------------------------------------------------

    def _pick_bucket(self, buckets, n, what):
        for b in buckets:
            if b >= n:
                return b
        raise MXNetError(
            f"serving: {what} {n} exceeds the largest bucket "
            f"{buckets[-1]} (buckets {buckets})")

    def serve_group(self, prompts, max_new_tokens, temperature=None,
                    rng=None):
        """Serve one coalesced group.  ``prompts``: list of 1-D int
        sequences (mixed lengths OK); ``max_new_tokens``: int or
        per-request list.  Returns ``(outputs, timings)`` where
        outputs[i] is the i-th request's generated tokens (np.int32)
        and timings carries the per-request record fields
        (prefill_us, decode_us_per_token, bucket, padded_fraction)."""
        import numpy as np

        n = len(prompts)
        if n == 0:
            return [], {}
        per_req = [max_new_tokens] * n \
            if isinstance(max_new_tokens, int) else list(max_new_tokens)
        if len(per_req) != n or any(k < 1 for k in per_req):
            raise MXNetError("serving: max_new_tokens must be a positive "
                             "int or one per prompt")
        steps = max(per_req)
        B = self._pick_bucket(self.batch_buckets, n, "group size")
        lens = np.ones(B, np.int32)     # pad rows hold one dummy token
        for i, p in enumerate(prompts):
            if len(p) < 1:
                raise MXNetError("serving: empty prompt")
            lens[i] = len(p)
        Tmax = int(lens[:n].max())
        if Tmax + steps > self._W:
            raise MXNetError(
                f"serving: {Tmax} prompt + {steps} new tokens exceed "
                f"the cache window max_length={self._W}")
        S = self._pick_bucket(self.prefill_buckets, Tmax, "prompt length")
        toks = np.zeros((B, S), np.int32)
        for i, p in enumerate(prompts):
            toks[i, :lens[i]] = np.asarray(p, np.int32)
        t0 = time.perf_counter()
        t_prefill0 = time.time()
        ck, cv = self.init_cache(B)
        ck, cv, logits = self._call(B, S, ck, cv,
                                    np.zeros(B, np.int32), toks)
        last = np.asarray(logits)[np.arange(B), lens - 1]
        prefill_us = (time.perf_counter() - t0) * 1e6
        t1 = time.perf_counter()
        t_decode0 = time.time()
        out = np.zeros((B, steps), np.int32)
        for j in range(steps):
            nxt = _sample(last, temperature, rng)
            out[:, j] = nxt
            if j < steps - 1:      # the last token needs no cache step
                ck, cv, logits = self._call(B, 1, ck, cv, lens + j,
                                            nxt[:, None])
                last = np.asarray(logits)[:, 0]
        decode_us = (time.perf_counter() - t1) * 1e6
        timings = {
            "prefill_us": prefill_us,
            "decode_us_per_token": decode_us / max(1, steps),
            "bucket": [int(B), int(S)],
            "padded_fraction": round(
                1.0 - float(lens[:n].sum()) / float(B * S), 4),
            "generation": self.generation,
            # wall-clock stage starts + total decode time: span
            # material for obs/spans.py (host clock reads only)
            "t_prefill0": t_prefill0,
            "t_decode0": t_decode0,
            "decode_us": decode_us,
        }
        return [out[i, :per_req[i]].copy() for i in range(n)], timings
