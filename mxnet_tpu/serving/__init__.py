"""Low-latency serving tier: AOT bucketed decode, continuous batching,
KV-cache, hot model reload.  See docs/serving.md."""

from .batcher import (ContinuousBatcher, DeadlineExceeded,
                      ServerOverloaded, max_delay_ms_from_env,
                      max_queue_from_env)
from .engine import (ServingEngine, batch_buckets_from_env, compile_count,
                     dispatch_count, prefill_buckets_for, reset_counters,
                     state_for_serving, trace_count)
from .replica import FleetWatcher, FrontDoor, ReplicaServer

__all__ = [
    "ServingEngine", "ContinuousBatcher", "ReplicaServer", "FrontDoor",
    "FleetWatcher", "ServerOverloaded", "DeadlineExceeded",
    "state_for_serving", "batch_buckets_from_env", "prefill_buckets_for",
    "max_delay_ms_from_env", "max_queue_from_env", "trace_count",
    "compile_count", "dispatch_count", "reset_counters",
]
