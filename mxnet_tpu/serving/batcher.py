"""Continuous batcher: coalesce concurrent requests into bucketed groups.

One daemon thread owns the engine.  Submitters get a
``concurrent.futures.Future`` back immediately; the loop collects
requests until either the latency deadline (``MXTPU_SERVE_MAX_DELAY_MS``
past the FIRST queued request — later arrivals don't extend it) or the
largest batch bucket is reached, serves the group through ONE bucketed
AOT dispatch sequence, and resolves every future.

The deadline is the latency/throughput dial: 0 serves each request the
moment the engine is free (lowest latency, no coalescing); a few ms lets
concurrent clients share a prefill+decode pass (the padded rows are
nearly free, so tokens/sec scales with the bucket fill).

``before_batch`` runs between groups with the engine idle — the hook
serving/replica.py uses to hot-swap reloaded weights with zero dropped
requests.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from concurrent.futures import Future

from .. import telemetry
from ..base import MXNetError, getenv_int
from ..obs.spans import Trace


class ServerOverloaded(MXNetError):
    """submit() on a full admission queue: the request is shed
    immediately instead of growing tail latency unboundedly."""


class DeadlineExceeded(MXNetError):
    """The request's deadline passed before it reached the engine."""


def max_delay_ms_from_env(default=5.0):
    raw = os.environ.get("MXTPU_SERVE_MAX_DELAY_MS")
    if not raw:
        return default
    try:
        return max(0.0, float(raw))
    except ValueError:
        return default


def max_queue_from_env(default=256):
    return max(1, getenv_int("MXTPU_SERVE_MAX_QUEUE", default))


_SHUTDOWN = object()    # close() sentinel: wakes the blocked collector


class _Request:
    __slots__ = ("prompt", "max_new_tokens", "future", "t_enqueue",
                 "deadline", "trace", "span", "qspan")

    def __init__(self, prompt, max_new_tokens, deadline_ms=None,
                 trace=None, replica_id=None):
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.future = Future()
        self.t_enqueue = time.perf_counter()
        self.deadline = (None if deadline_ms is None
                         else self.t_enqueue + float(deadline_ms) / 1e3)
        # span tree (obs/spans.py): a FrontDoor-minted trace arrives
        # with an open root; a direct submit roots at the batcher
        t_wall = time.time()
        if trace is None:
            trace = Trace()
            self.span = trace.begin("batcher", t0=t_wall,
                                    replica_id=replica_id)
        else:
            self.span = trace.begin("batcher", parent=trace.root(),
                                    t0=t_wall, replica_id=replica_id)
        self.trace = trace
        self.qspan = trace.begin("queue", parent=self.span, t0=t_wall)


class ContinuousBatcher:
    """Queue + serving loop over a ServingEngine.

    ``submit(prompt, max_new_tokens)`` → Future resolving to a dict:
    ``tokens`` (np.int32 generated ids) plus the per-request record
    fields (queue_us, prefill_us, decode_us_per_token, bucket,
    padded_fraction, generation).
    """

    def __init__(self, engine, max_delay_ms=None, max_batch=None,
                 before_batch=None, temperature=None, rng=None,
                 max_queue=None, replica_id=None):
        self.engine = engine
        self.replica_id = replica_id
        self.max_delay_ms = (max_delay_ms_from_env()
                             if max_delay_ms is None else max_delay_ms)
        self.max_batch = max_batch or max(engine.batch_buckets)
        self.max_queue = (max_queue_from_env()
                          if max_queue is None else max(1, int(max_queue)))
        self.before_batch = before_batch
        self._temperature = temperature
        self._rng = rng
        self._q = queue.Queue(maxsize=self.max_queue)
        self._stop = threading.Event()
        self.groups_served = 0
        self.requests_served = 0
        self.shed = 0
        self.deadline_exceeded = 0
        self._thread = threading.Thread(target=self._loop,
                                        name="mxtpu-batcher", daemon=True)
        self._thread.start()

    def submit(self, prompt, max_new_tokens=16, deadline_ms=None,
               trace=None):
        """Enqueue one request → Future.  Raises
        :class:`ServerOverloaded` when the admission queue is full (the
        caller — or its FrontDoor — decides whether to retry elsewhere);
        a ``deadline_ms`` budget resolves the future with
        :class:`DeadlineExceeded` if group formation can't reach it in
        time.  ``trace``: an obs.spans.Trace minted upstream (the
        FrontDoor) — batcher/prefill/decode spans attach under its
        root; None mints a batcher-rooted trace."""
        if self._stop.is_set():
            raise RuntimeError("batcher is closed")
        req = _Request(prompt, max_new_tokens, deadline_ms,
                       trace=trace, replica_id=self.replica_id)
        try:
            self._q.put_nowait(req)
        except queue.Full:
            self.shed += 1
            telemetry.count("serving.queue_full")
            telemetry.event("queue_full", depth=self.max_queue)
            raise ServerOverloaded(
                f"serving queue full ({self.max_queue} pending); "
                f"request shed") from None
        return req.future

    def _collect(self):
        """Block for the first request, then coalesce until the deadline
        or the largest bucket fills.  Blocking (not polling): an idle
        replica costs zero CPU; close() wakes the block with a
        sentinel — _collect returns None and the loop exits to drain."""
        first = self._q.get()
        if first is _SHUTDOWN:
            return None
        group = [first]
        deadline = first.t_enqueue + self.max_delay_ms / 1e3
        while len(group) < self.max_batch:
            wait = deadline - time.perf_counter()
            if wait <= 0:
                # deadline hit — grab whatever is already queued, no wait
                try:
                    while len(group) < self.max_batch:
                        item = self._q.get_nowait()
                        if item is _SHUTDOWN:
                            break       # _loop re-checks _stop next
                        group.append(item)
                except queue.Empty:
                    pass
                break
            try:
                item = self._q.get(timeout=wait)
            except queue.Empty:
                break
            if item is _SHUTDOWN:
                break
            group.append(item)
        return group

    def _expire(self, group, now):
        """Resolve requests whose deadline passed during queueing with
        DeadlineExceeded BEFORE they cost a dispatch slot; returns the
        still-live remainder."""
        live = []
        for r in group:
            if r.deadline is None or now <= r.deadline:
                live.append(r)
                continue
            self.deadline_exceeded += 1
            telemetry.count("serving.deadline_exceeded")
            queue_us = (now - r.t_enqueue) * 1e6
            r.qspan.close(dur_us=queue_us)
            r.span.attrs["deadline_exceeded"] = True
            r.trace.close_open()
            telemetry.request_record(
                queue_us=queue_us,
                prefill_us=0.0, decode_us_per_token=0.0,
                bucket=[1, 1], padded_fraction=0.0, new_tokens=0,
                deadline_exceeded=True, replica_id=self.replica_id,
                **r.trace.to_fields())
            if not r.future.cancelled():
                r.future.set_exception(DeadlineExceeded(
                    f"deadline passed after "
                    f"{(now - r.t_enqueue) * 1e3:.1f} ms in queue"))
        return live

    def _serve(self, group):
        t_batch = time.perf_counter()
        group = self._expire(group, t_batch)
        if not group:
            return
        try:
            if self.before_batch is not None:
                self.before_batch()
            outs, timings = self.engine.serve_group(
                [r.prompt for r in group],
                [r.max_new_tokens for r in group],
                temperature=self._temperature, rng=self._rng)
        except BaseException as exc:  # resolve ALL futures, never hang
            for r in group:
                if not r.future.cancelled():
                    r.future.set_exception(exc)
            return
        self.groups_served += 1
        self.requests_served += len(group)
        t_done = time.time()
        for r, toks in zip(group, outs):
            queue_us = (t_batch - r.t_enqueue) * 1e6
            rec = dict(timings)
            rec["queue_us"] = queue_us
            rec["tokens"] = toks
            # close the request's span tree from the group's stage
            # clocks — no extra timing work, the engine already took
            # these readings (obs/spans.py)
            r.qspan.close(dur_us=queue_us)
            r.trace.begin("prefill", parent=r.span,
                          t0=timings.get("t_prefill0"),
                          bucket=f"{timings['bucket'][0]}x"
                                 f"{timings['bucket'][1]}",
                          generation=timings["generation"]) \
                .close(dur_us=timings["prefill_us"])
            r.trace.begin("decode", parent=r.span,
                          t0=timings.get("t_decode0"),
                          new_tokens=len(toks)) \
                .close(dur_us=timings.get(
                    "decode_us",
                    timings["decode_us_per_token"] * len(toks)))
            r.trace.close_open(t_end=t_done)
            telemetry.request_record(
                queue_us=queue_us,
                prefill_us=timings["prefill_us"],
                decode_us_per_token=timings["decode_us_per_token"],
                bucket=timings["bucket"],
                padded_fraction=timings["padded_fraction"],
                new_tokens=len(toks),
                generation=timings["generation"],
                deadline_exceeded=False, replica_id=self.replica_id,
                **r.trace.to_fields())
            if not r.future.cancelled():
                r.future.set_result(rec)

    def _loop(self):
        while not self._stop.is_set():
            group = self._collect()
            if group is None:
                break
            if group:
                self._serve(group)
        # drain: resolve what is left rather than abandoning futures
        while True:
            try:
                group = [self._q.get_nowait()]
            except queue.Empty:
                break
            if group[0] is not _SHUTDOWN:
                self._serve(group)

    def close(self, timeout=30.0):
        """Stop the loop; queued requests are still served (drained)."""
        self._stop.set()
        # wake the blocked collector; the loop is consuming, so a full
        # queue clears within the timeout
        deadline = time.perf_counter() + timeout
        while self._thread.is_alive():
            try:
                self._q.put(_SHUTDOWN, timeout=0.1)
                break
            except queue.Full:
                if time.perf_counter() > deadline:
                    break
        self._thread.join(timeout)
