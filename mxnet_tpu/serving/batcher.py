"""Continuous batcher: coalesce concurrent requests into bucketed groups.

One daemon thread owns the engine.  Submitters get a
``concurrent.futures.Future`` back immediately; the loop collects
requests until either the latency deadline (``MXTPU_SERVE_MAX_DELAY_MS``
past the FIRST queued request — later arrivals don't extend it) or the
largest batch bucket is reached, serves the group through ONE bucketed
AOT dispatch sequence, and resolves every future.

The deadline is the latency/throughput dial: 0 serves each request the
moment the engine is free (lowest latency, no coalescing); a few ms lets
concurrent clients share a prefill+decode pass (the padded rows are
nearly free, so tokens/sec scales with the bucket fill).

``before_batch`` runs between groups with the engine idle — the hook
serving/replica.py uses to hot-swap reloaded weights with zero dropped
requests.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from concurrent.futures import Future

from .. import telemetry


def max_delay_ms_from_env(default=5.0):
    raw = os.environ.get("MXTPU_SERVE_MAX_DELAY_MS")
    if not raw:
        return default
    try:
        return max(0.0, float(raw))
    except ValueError:
        return default


class _Request:
    __slots__ = ("prompt", "max_new_tokens", "future", "t_enqueue")

    def __init__(self, prompt, max_new_tokens):
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.future = Future()
        self.t_enqueue = time.perf_counter()


class ContinuousBatcher:
    """Queue + serving loop over a ServingEngine.

    ``submit(prompt, max_new_tokens)`` → Future resolving to a dict:
    ``tokens`` (np.int32 generated ids) plus the per-request record
    fields (queue_us, prefill_us, decode_us_per_token, bucket,
    padded_fraction, generation).
    """

    def __init__(self, engine, max_delay_ms=None, max_batch=None,
                 before_batch=None, temperature=None, rng=None):
        self.engine = engine
        self.max_delay_ms = (max_delay_ms_from_env()
                             if max_delay_ms is None else max_delay_ms)
        self.max_batch = max_batch or max(engine.batch_buckets)
        self.before_batch = before_batch
        self._temperature = temperature
        self._rng = rng
        self._q = queue.Queue()
        self._stop = threading.Event()
        self.groups_served = 0
        self.requests_served = 0
        self._thread = threading.Thread(target=self._loop,
                                        name="mxtpu-batcher", daemon=True)
        self._thread.start()

    def submit(self, prompt, max_new_tokens=16):
        if self._stop.is_set():
            raise RuntimeError("batcher is closed")
        req = _Request(prompt, max_new_tokens)
        self._q.put(req)
        return req.future

    def _collect(self):
        """Block for the first request, then coalesce until the deadline
        or the largest bucket fills."""
        try:
            first = self._q.get(timeout=0.05)
        except queue.Empty:
            return []
        group = [first]
        deadline = first.t_enqueue + self.max_delay_ms / 1e3
        while len(group) < self.max_batch:
            wait = deadline - time.perf_counter()
            if wait <= 0:
                # deadline hit — grab whatever is already queued, no wait
                try:
                    while len(group) < self.max_batch:
                        group.append(self._q.get_nowait())
                except queue.Empty:
                    pass
                break
            try:
                group.append(self._q.get(timeout=wait))
            except queue.Empty:
                break
        return group

    def _serve(self, group):
        t_batch = time.perf_counter()
        try:
            if self.before_batch is not None:
                self.before_batch()
            outs, timings = self.engine.serve_group(
                [r.prompt for r in group],
                [r.max_new_tokens for r in group],
                temperature=self._temperature, rng=self._rng)
        except BaseException as exc:  # resolve ALL futures, never hang
            for r in group:
                if not r.future.cancelled():
                    r.future.set_exception(exc)
            return
        self.groups_served += 1
        self.requests_served += len(group)
        for r, toks in zip(group, outs):
            queue_us = (t_batch - r.t_enqueue) * 1e6
            rec = dict(timings)
            rec["queue_us"] = queue_us
            rec["tokens"] = toks
            telemetry.request_record(
                queue_us=queue_us,
                prefill_us=timings["prefill_us"],
                decode_us_per_token=timings["decode_us_per_token"],
                bucket=timings["bucket"],
                padded_fraction=timings["padded_fraction"],
                new_tokens=len(toks),
                generation=timings["generation"])
            if not r.future.cancelled():
                r.future.set_result(rec)

    def _loop(self):
        while not self._stop.is_set():
            group = self._collect()
            if group:
                self._serve(group)
        # drain: resolve what is left rather than abandoning futures
        while True:
            try:
                group = [self._q.get_nowait()]
            except queue.Empty:
                break
            self._serve(group)

    def close(self, timeout=30.0):
        """Stop the loop; queued requests are still served (drained)."""
        self._stop.set()
        self._thread.join(timeout)
