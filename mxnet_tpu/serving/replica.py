"""Multi-replica front door with hot model reload.

``ReplicaServer`` = one engine + one ContinuousBatcher + a checkpoint
poller.  The poller watches an AsyncCheckpointer directory for a newer
committed ``MANIFEST.json`` (checkpoint.latest_manifest_step), restores
the state dict OFF the serving thread, and stages it; the batcher's
``before_batch`` hook applies the staged swap between groups — the
engine's weights are program *arguments*, so the swap is an array
replacement, no recompile, and in-flight requests are never dropped
(they either run on the old generation or the new one, never on a
half-swapped set).

``FrontDoor`` spreads requests over a replica group round-robin,
supervised by the PR 8 health plane: each replica publishes heartbeats
to the shared FileKV, a FailureDetector marks silent replicas dead, and
submission fails over to the next live replica.
"""

from __future__ import annotations

import os
import threading
import time

from .. import telemetry
from ..base import MXNetError, getenv_int
from ..obs.spans import Trace
from .batcher import ContinuousBatcher, ServerOverloaded


def reload_poll_ms_from_env(default=200):
    return max(1, getenv_int("MXTPU_SERVE_RELOAD_POLL_MS", default))


class ReplicaServer:
    """One serving replica: batcher + checkpoint-driven hot reload.

    ``ckpt_dir``: AsyncCheckpointer directory to poll (None disables
    reload).  ``kv``/``rank``: FileKV control plane for heartbeats (the
    FrontDoor's failure detector watches them).
    """

    def __init__(self, engine, ckpt_dir=None, poll_ms=None, kv=None,
                 rank=0, max_delay_ms=None, max_batch=None,
                 temperature=None, rng=None):
        self.engine = engine
        self.rank = rank
        self._ckpt_dir = os.fspath(ckpt_dir) if ckpt_dir else None
        self._poll_ms = (reload_poll_ms_from_env()
                         if poll_ms is None else poll_ms)
        self.loaded_step = None
        self._fetched_step = None    # newest step the poller restored
        self._served_epoch = None    # gang_epoch of the staged manifest
        self._staged = None          # (step, state) awaiting swap
        self._staged_lock = threading.Lock()
        self._stop = threading.Event()
        self.reloads = 0
        self.batcher = ContinuousBatcher(
            engine, max_delay_ms=max_delay_ms, max_batch=max_batch,
            before_batch=self._maybe_swap, temperature=temperature,
            rng=rng, replica_id=rank)
        self._hb = None
        if kv is not None:
            from ..resilience import HeartbeatPublisher

            self._hb = HeartbeatPublisher(kv, rank)
            self._hb.start()
        self._poller = None
        if self._ckpt_dir is not None:
            self._poller = threading.Thread(
                target=self._poll_loop, name=f"mxtpu-reload-{rank}",
                daemon=True)
            self._poller.start()

    def submit(self, prompt, max_new_tokens=16, deadline_ms=None,
               trace=None):
        return self.batcher.submit(prompt, max_new_tokens,
                                   deadline_ms=deadline_ms, trace=trace)

    # -- hot reload ------------------------------------------------------------

    def poll_once(self):
        """Check the manifest; verify, restore + stage a newer step.
        Runs on the poller thread — the expensive host restore happens
        here, never on the serving thread."""
        from .. import checkpoint

        step = checkpoint.latest_manifest_step(self._ckpt_dir)
        # _fetched_step (poller-thread-private) is the dedup, NOT
        # loaded_step: a step staged but not yet swapped by the batcher
        # must not be restored (and swapped) a second time
        if step is None or step == self._fetched_step:
            return False
        ck = checkpoint.AsyncCheckpointer(
            self._ckpt_dir, rank=0, world_size=1)
        if not self._verify_reload(ck, step):
            # a corrupt checkpoint is REJECTED, never served; the step
            # still dedups (a bad file on disk will not un-corrupt —
            # without this the poller would re-verify it every 200ms
            # forever).  A subsequent GOOD step reloads normally.
            self._fetched_step = step
            return False
        state = ck.restore(step=step)
        self._fetched_step = step
        with self._staged_lock:
            self._staged = (step, state)
        return True

    def _verify_reload(self, ck, step):
        """Integrity gate ahead of the swap: re-read the manifest with
        every shard CRC-checked, and audit its attestation-ledger stamp
        (integrity.verify_provenance) when one is present.  Emits
        ``serving_reload_rejected`` and returns False on any failure."""
        from .. import integrity
        from ..resilience import CheckpointCorrupt

        try:
            m = ck.verify(step)
        except CheckpointCorrupt as exc:
            telemetry.event("serving_reload_rejected", rank=self.rank,
                            step=int(step), reason=str(exc)[:200])
            return False
        ok, why = integrity.verify_provenance(m)
        if not ok:
            telemetry.event("serving_reload_rejected", rank=self.rank,
                            step=int(step),
                            reason=f"provenance: {why}"[:200])
            return False
        # epoch fence (schema v8): never serve a manifest from a gang
        # epoch OLDER than the one already served — a fenced trainer's
        # stale commit (partition minority, resumed zombie) must not
        # roll the serving weights backwards.  Manifests without the
        # stamp (pre-v8, or gang-less trainers) pass unchanged.
        epoch = m.get("gang_epoch")
        if epoch is not None and self._served_epoch is not None \
                and int(epoch) < self._served_epoch:
            telemetry.event("serving_reload_rejected", rank=self.rank,
                            step=int(step),
                            reason=f"stale_epoch: manifest gang_epoch "
                                   f"{int(epoch)} < served "
                                   f"{self._served_epoch}"[:200])
            return False
        if epoch is not None:
            self._served_epoch = int(epoch)
        return True

    def _poll_loop(self):
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception as exc:
                telemetry.event("serving_reload_error", rank=self.rank,
                                error=f"{type(exc).__name__}: {exc}")
            self._stop.wait(self._poll_ms / 1e3)

    def _maybe_swap(self):
        """Apply a staged reload — called by the batcher BETWEEN groups,
        with the engine idle, so no request ever sees a half-swap."""
        with self._staged_lock:
            staged, self._staged = self._staged, None
        if staged is None:
            return
        step, state = staged
        self.engine.reload_from_state(state, step=step)
        self.loaded_step = step
        self.reloads += 1

    def close(self, timeout=30.0):
        self._stop.set()
        if self._poller is not None:
            self._poller.join(timeout)
        if self._hb is not None:
            self._hb.stop()
        self.batcher.close(timeout)


class FrontDoor:
    """Round-robin request router over a replica group with failover.

    With a FileKV the PR 8 FailureDetector confirms dead replicas from
    heartbeat silence; without one, only local submit failures mark a
    replica out.
    """

    def __init__(self, replicas, kv=None, timeout=None):
        if not replicas:
            raise MXNetError("FrontDoor: need at least one replica")
        self.replicas = list(replicas)
        self._rr = 0
        self._lock = threading.Lock()
        self._failed = set()
        self._detector = None
        if kv is not None:
            from ..resilience import FailureDetector

            self._detector = FailureDetector(
                kv, -1, [r.rank for r in self.replicas],
                timeout=timeout)

    def alive(self):
        """Replicas not confirmed dead (detector) nor locally failed."""
        dead = set(self._failed)
        if self._detector is not None:
            dead |= set(self._detector.poll())
        return [r for r in self.replicas if r.rank not in dead]

    def submit(self, prompt, max_new_tokens=16, deadline_ms=None):
        """Submit to the next live replica; fail over on submit error.

        A :class:`ServerOverloaded` shed is NOT a replica failure — the
        replica is healthy, just full — so it is retried once on the
        next replica without marking anyone out, then re-raised for the
        client to back off."""
        live = self.alive()
        if not live:
            raise MXNetError("FrontDoor: no live replicas")
        with self._lock:
            start = self._rr
            self._rr += 1
        # the distributed trace is minted HERE — the fleet's ingress —
        # so a shed-retry onto another replica stays ONE causal tree
        # with the retry visible as a root attr (obs/spans.py)
        trace = Trace()
        root = trace.begin("frontdoor")
        last_exc = None
        shed = 0
        for i in range(len(live)):
            r = live[(start + i) % len(live)]
            try:
                fut = r.submit(prompt, max_new_tokens,
                               deadline_ms=deadline_ms, trace=trace)
                if shed:
                    root.attrs["retries"] = shed
                return fut
            except ServerOverloaded as exc:
                last_exc = exc
                shed += 1
                telemetry.event("serving_request_shed", rank=r.rank)
                if shed > 1:        # one retry on the next replica
                    break
            except Exception as exc:
                last_exc = exc
                self._failed.add(r.rank)
                telemetry.event("serving_replica_failover", rank=r.rank,
                                error=f"{type(exc).__name__}: {exc}")
        if isinstance(last_exc, ServerOverloaded):
            raise last_exc
        raise MXNetError(
            f"FrontDoor: every replica refused the request "
            f"(last: {last_exc})")

    def close(self, timeout=30.0):
        for r in self.replicas:
            r.close(timeout)


class FleetWatcher:
    """Turns freed training chips into serving capacity.

    Watches the gang KV for ``chips/freed/<rank>`` announcements
    (written by ``resilience.announce_freed_chips`` after a ScalePolicy
    drain), claims each one — delete the announcement, record
    ``chips/claimed/<rank>`` — and calls ``spawn(announcement)`` to
    bring up a replica on the freed chips.  ``spawn`` returns the
    replica object (kept in ``self.replicas``) or None to decline.

    One watcher per fleet: the claim is delete-based, so concurrent
    watchers could double-claim — run it next to the FrontDoor.
    """

    def __init__(self, kv, spawn, poll_s=0.5):
        self.kv = kv
        self.spawn = spawn
        self.poll_s = float(poll_s)
        self.replicas = []
        self.claimed = 0
        self._stop = threading.Event()
        self._thread = None

    def poll_once(self):
        """Scan + claim + spawn; returns the replicas spawned now."""
        spawned = []
        for key, _ in self.kv.scan("chips/freed"):
            rec = self.kv.get_json(key)
            if not isinstance(rec, dict) or rec.get("rank") is None:
                continue
            rank = int(rec["rank"])
            self.kv.delete(key)
            self.kv.put_json(f"chips/claimed/{rank}",
                             {"rank": rank, "t": time.time()})
            self.claimed += 1
            rep = self.spawn(rec)
            telemetry.event("serving_replica_spawned", rank=rank,
                            count=int(rec.get("count", 1)),
                            spawned=rep is not None)
            if rep is not None:
                self.replicas.append(rep)
                spawned.append(rep)
        return spawned

    def start(self):
        if self._thread is not None:
            return self
        self._thread = threading.Thread(target=self._loop,
                                        name="mxtpu-fleet-watcher",
                                        daemon=True)
        self._thread.start()
        return self

    def _loop(self):
        while not self._stop.is_set():
            try:
                self.poll_once()
            except Exception as exc:    # noqa: BLE001 — keep watching
                telemetry.event("fleet_watcher_error",
                                error=f"{type(exc).__name__}: {exc}")
            self._stop.wait(self.poll_s)

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


def _wait_all(futures, timeout=None):
    """Resolve a list of serving futures → list of result dicts."""
    deadline = None if timeout is None else time.perf_counter() + timeout
    out = []
    for f in futures:
        left = None if deadline is None \
            else max(0.0, deadline - time.perf_counter())
        out.append(f.result(timeout=left))
    return out
