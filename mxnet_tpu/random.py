"""PRNG management.

Reference parity: src/resource.cc (kRandom/kParallelRandom resources),
python/mxnet/random.py (mx.random.seed).

TPU-first design: JAX's counter-based threefry PRNG replaces the reference's
per-device RNG states.  Eager ops draw keys from a global stateful key chain
(split-per-call, like the reference's global RandomGenerator); traced code
(hybridized blocks / jitted steps) draws from a *key scope* — a thread-local
stack established by the CachedOp with a key that is an argument of the jit,
so randomness is functional under compilation and refreshes per invocation.
"""

from __future__ import annotations

import threading

import numpy as _np


class _KeyState(threading.local):
    def __init__(self):
        self.key = None
        self.scope: list = []  # (key, counter-box) entries


_STATE = _KeyState()


def seed(seed_state: int, ctx="all") -> None:
    """mx.random.seed — reseeds the global eager key chain AND numpy's
    global RNG (initializers draw from numpy; reference mx.random.seed
    seeds all device RNGs so weight init is reproducible)."""
    import jax

    _STATE.key = jax.random.PRNGKey(int(seed_state))
    _np.random.seed(int(seed_state) % (2 ** 32))


def _global_key():
    import jax

    if _STATE.key is None:
        _STATE.key = jax.random.PRNGKey(
            int(_np.random.SeedSequence().entropy % (2 ** 31)))
    _STATE.key, sub = jax.random.split(_STATE.key)
    return sub


class key_scope:
    """Context manager routing `next_key()` to folds of a base key.

    The base key may be a tracer (CachedOp passes its per-call key argument),
    which makes every random op inside a trace a pure function of that key.
    """

    def __init__(self, base_key):
        self.base_key = base_key

    def __enter__(self):
        _STATE.scope.append([self.base_key, 0])
        return self

    def __exit__(self, *exc):
        _STATE.scope.pop()


def next_key():
    """Fetch a fresh PRNG key: scope-folded if inside a key_scope (traceable),
    else split from the global chain (eager)."""
    import jax

    if _STATE.scope:
        entry = _STATE.scope[-1]
        entry[1] += 1
        return jax.random.fold_in(entry[0], entry[1])
    return _global_key()


def in_key_scope() -> bool:
    return bool(_STATE.scope)


# numpy-compatible helpers used across the frontend
def np_seed(seed_state):
    _np.random.seed(seed_state)


# -- module-level samplers (reference: python/mxnet/random.py delegates
# to the ndarray.random generated wrappers) --------------------------------

def _delegate(name):
    def fn(*args, **kwargs):
        from .ndarray import random as _ndr

        return getattr(_ndr, name)(*args, **kwargs)

    fn.__name__ = name
    fn.__doc__ = f"mx.random.{name} (delegates to mx.nd.random.{name})"
    return fn


uniform = _delegate("uniform")
normal = _delegate("normal")
randn = _delegate("randn")
randint = _delegate("randint")
poisson = _delegate("poisson")
exponential = _delegate("exponential")
gamma = _delegate("gamma")
multinomial = _delegate("multinomial")
negative_binomial = _delegate("negative_binomial")
generalized_negative_binomial = _delegate("generalized_negative_binomial")
shuffle = _delegate("shuffle")
