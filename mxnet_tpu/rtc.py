"""Runtime kernel compilation (reference: python/mxnet/rtc.py —
CudaModule/CudaKernel over NVRTC, src/common/rtc.cc).

TPU-first redesign: the runtime-compiled-kernel facility on TPU is
Pallas (Mosaic), not NVRTC.  ``PallasModule`` takes a python source
string defining pallas kernels, compiles it at runtime, and exposes
get_kernel with the reference's launch-style call signature.
``CudaModule`` remains as an API shim that raises with guidance, so
ported scripts fail with an actionable message instead of an
AttributeError.
"""

from __future__ import annotations

from .base import MXNetError


class CudaModule:
    """Reference signature shim.  CUDA source cannot target the MXU;
    port kernels to Pallas and use PallasModule."""

    def __init__(self, source, options=(), exports=()):
        raise MXNetError(
            "mx.rtc.CudaModule compiles CUDA C, which has no TPU "
            "target.  Port the kernel to Pallas and use "
            "mx.rtc.PallasModule(source, exports=[...]) — the kernel "
            "body keeps the same grid/block mental model "
            "(pl.program_id, BlockSpecs) on the MXU/VPU.")


class PallasKernel:
    """One compiled pallas kernel (reference analog: CudaKernel)."""

    def __init__(self, fn, name):
        self._fn = fn
        self.name = name

    def launch(self, args, ctx=None, grid_dims=None, block_dims=None,
               shared_mem=0):
        """Reference CudaKernel.launch signature; grid/block dims are
        advisory on TPU (the kernel's own BlockSpecs/grid govern)."""
        from .ndarray.ndarray import NDArray, _from_jax

        raw = [a._data if isinstance(a, NDArray) else a for a in args]
        out = self._fn(*raw)
        if isinstance(out, (tuple, list)):
            return [_from_jax(o) for o in out]
        return _from_jax(out)

    __call__ = launch


class PallasModule:
    """Compile python source containing jax/pallas kernels at runtime.

    source: python code; exports: names of callables to expose.  Each
    exported callable takes/returns jax arrays (wrap pl.pallas_call
    inside).  Example::

        src = '''
        import jax, jax.numpy as jnp
        from jax.experimental import pallas as pl

        def _add1(x_ref, o_ref):
            o_ref[...] = x_ref[...] + 1.0

        def add_one(x):
            return pl.pallas_call(
                _add1, out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
                interpret=True)(x)
        '''
        mod = mx.rtc.PallasModule(src, exports=['add_one'])
        y = mod.get_kernel('add_one').launch([x])
    """

    def __init__(self, source, options=(), exports=()):
        self._namespace = {}
        try:
            exec(compile(source, "<rtc.PallasModule>", "exec"),
                 self._namespace)
        except Exception as e:
            raise MXNetError(f"PallasModule compilation failed: {e}")
        self._exports = list(exports)
        for name in self._exports:
            if name not in self._namespace:
                raise MXNetError(
                    f"PallasModule: export '{name}' not defined by the "
                    "source")

    def get_kernel(self, name, signature=None):
        # only declared exports are kernels — without the check an
        # empty exports list would expose every namespace entry
        # (imports, ref-kernels, __builtins__) as launchable
        if name not in self._exports or name not in self._namespace:
            raise MXNetError(
                f"PallasModule: no exported kernel '{name}' (declare it "
                "in exports=[...])")
        return PallasKernel(self._namespace[name], name)
