"""Host-side id preparation for captured sparse-embedding steps.

The captured program cannot compute ``np.unique`` — shapes must be
static under jit — so the host computes, per step and per table:

    ids  = clip(astype(int32, feature-slice(batch)), 0, vocab-1).ravel()
    uniq, inv = np.unique(ids, return_inverse=True)

(exactly the clip/cast order of the eager `ops.indexing
.sparse_embedding` op, so the two paths agree on which row every id
reads), then pads ``uniq`` to a power-of-two bucket with the sentinel
id ``vocab``.  The sentinel is OUT of range on purpose: the in-program
pre-gather reads it with ``mode='clip'`` (deterministic, no NaN), and
every scatter back to the table drops out-of-bounds rows, so padded
slots write nothing.  The bucket size joins the capture key — retraces
are bounded by the number of distinct buckets, not by per-batch unique
counts.

The DevicePrefetcher's producer thread calls `prepare_step` one batch
ahead and stashes the result (`stash_prep`/`pop_prep`), overlapping the
unique/inverse work — the dominant host_prep cost of a sparse step —
with the current step's device compute.
"""

from __future__ import annotations

import os
import time
from contextlib import contextmanager

import numpy as _np


def sparse_captured_enabled() -> bool:
    """MXTPU_SPARSE_CAPTURED gate (default on); 0/false/off pins
    sparse_grad=True configurations to the eager row-sparse oracle."""
    return os.environ.get("MXTPU_SPARSE_CAPTURED", "1").lower() \
        not in ("0", "false", "off", "")


def unique_bucket_env() -> int:
    """MXTPU_UNIQUE_BUCKET: fixed unique-count bucket (one capture
    signature for every batch whose unique count fits), or 0 (default)
    for automatic next-power-of-two bucketing.  An autotune knob
    (autotune/space.py, layer='program'): a changed value re-captures
    via `program_knob_values` in the capture key."""
    try:
        return max(0, int(os.environ.get("MXTPU_UNIQUE_BUCKET", "0")))
    except ValueError:
        return 0


def bucket_for(n_real: int):
    """Padded unique-count bucket for ``n_real`` unique ids: the fixed
    MXTPU_UNIQUE_BUCKET when set (None when the batch does not fit —
    the caller falls back to the eager oracle, with a telemetry
    reason), else the next power of two."""
    fixed = unique_bucket_env()
    if fixed:
        return fixed if n_real <= fixed else None
    b = 1
    while b < max(int(n_real), 1):
        b *= 2
    return b


class SparsePrep:
    """One table's host-prepared lookup indices for one batch."""

    __slots__ = ("uniq", "inv", "bucket", "n_real", "n_ids", "vocab")

    def __init__(self, uniq, inv, bucket, n_real, n_ids, vocab):
        self.uniq = uniq          # np.int32 (bucket,) padded with vocab
        self.inv = inv            # np.int32 (n_ids,) into uniq
        self.bucket = int(bucket)
        self.n_real = int(n_real)
        self.n_ids = int(n_ids)
        self.vocab = int(vocab)


def _n_ids_of(shape, feature):
    """Flat id count a (batch) shape yields under a feature selector."""
    n = 1
    if feature is None:
        for d in shape:
            n *= int(d)
        return n
    for d in shape[:-1]:
        n *= int(d)
    if isinstance(feature, slice):
        start, stop, step = feature.indices(int(shape[-1]))
        return n * len(range(start, stop, step))
    return n


def extract_ids(data, feature, vocab):
    """Flat clipped int32 ids from a batch — the host twin of the eager
    op's ``clip(astype(int32, x), 0, vocab-1)`` (cast-then-clip order
    matters: both truncate floats toward zero first)."""
    arr = _np.asarray(getattr(data, "_data", data))
    if feature is not None:
        arr = arr[..., feature]
    ids = arr.astype(_np.int32)
    return _np.clip(ids, 0, vocab - 1).ravel()


def prepare_one(data, block):
    """`SparsePrep` for one ShardedEmbedding on one batch, or None when
    the unique count exceeds a fixed MXTPU_UNIQUE_BUCKET."""
    vocab = block._input_dim
    ids = extract_ids(data, block._feature, vocab)
    uniq, inv = _np.unique(ids, return_inverse=True)
    bucket = bucket_for(uniq.shape[0])
    if bucket is None:
        return None
    padded = _np.full((bucket,), vocab, _np.int32)
    padded[:uniq.shape[0]] = uniq
    return SparsePrep(uniq=padded, inv=inv.astype(_np.int32).ravel(),
                      bucket=bucket, n_real=uniq.shape[0],
                      n_ids=ids.size, vocab=vocab)


def find_sparse_embeddings(block):
    """{id(table param): ShardedEmbedding} over a block tree."""
    from .sharded import ShardedEmbedding

    found = {}

    def walk(b):
        if isinstance(b, ShardedEmbedding) and b._sparse_grad:
            found[id(b.weight)] = b
        for child in getattr(b, "_children", {}).values():
            walk(child)

    walk(block)
    return found


def sparse_capture_reason(trainer, block, sparse_params):
    """Why row-sparse params cannot enter the captured program, or None.
    ``sparse_params``: [(trainer index, Parameter)] with row_sparse
    grad_stype.  The returned string doubles as the ``sparse_fallback``
    telemetry reason."""
    from ..optimizer import optimizer as _optmod

    if not sparse_captured_enabled():
        return "sparse capture disabled (MXTPU_SPARSE_CAPTURED=0)"
    o = trainer._optimizer
    if type(o) not in (_optmod.SGD, _optmod.Adam):
        return f"optimizer {type(o).__name__} has no row-sparse " \
               "fused plan"
    if not getattr(o, "lazy_update", True):
        return "lazy_update=False densifies row-sparse gradients"
    emb = find_sparse_embeddings(block)
    for _i, p in sparse_params:
        if id(p) not in emb:
            return "sparse_grad=True parameter outside ShardedEmbedding"
    return None


def _prep_valid(pr, data, block):
    """A stashed prep is only usable if it still describes THIS batch
    shape, table, and bucket policy (the env knob may have moved)."""
    return (isinstance(pr, SparsePrep)
            and pr.vocab == block._input_dim
            and pr.n_ids == _n_ids_of(data.shape, block._feature)
            and bucket_for(pr.n_real) == pr.bucket)


def prepare_step(block, data, sparse_params):
    """Per-step host prep for every sparse table, prefetcher-stash
    aware.  Returns ``(preps, reason, lookup_us)``: ``preps`` is a list
    of `SparsePrep` aligned with ``sparse_params`` (None with a
    ``reason`` string on fallback); ``lookup_us`` is the host time
    spent here — near zero when the producer thread prepared ahead."""
    t0 = time.perf_counter()
    cached = pop_prep(data) or {}
    emb = find_sparse_embeddings(block)
    preps = []
    for _i, p in sparse_params:
        b = emb.get(id(p))
        if b is None:
            return (None,
                    "sparse_grad=True parameter outside ShardedEmbedding",
                    (time.perf_counter() - t0) * 1e6)
        pr = cached.get(id(p))
        if pr is not None and not _prep_valid(pr, data, b):
            pr = None
        if pr is None:
            pr = prepare_one(data, b)
        if pr is None:
            return (None,
                    "unique count exceeds MXTPU_UNIQUE_BUCKET="
                    f"{unique_bucket_env()}",
                    (time.perf_counter() - t0) * 1e6)
        preps.append(pr)
    return preps, None, (time.perf_counter() - t0) * 1e6


# -- prefetcher handoff (gluon/data/prefetcher.py producer thread) -------------
#
# Keyed by the YIELDED batch object's identity, holding a strong ref so
# the id cannot be recycled while the entry lives; one-shot pop on the
# consumer side, FIFO-bounded so an abandoned iterator cannot leak.

_PREP_CACHE = {}
_PREP_CACHE_MAX = 8


def stash_prep(data_nd, preps):
    """Producer-side: remember ``{id(table param): SparsePrep}`` for a
    batch about to be yielded."""
    while len(_PREP_CACHE) >= _PREP_CACHE_MAX:
        _PREP_CACHE.pop(next(iter(_PREP_CACHE)))
    _PREP_CACHE[id(data_nd)] = (data_nd, dict(preps))


def pop_prep(data_nd):
    """Consumer-side: the stashed preps for exactly this batch object,
    or None.  One-shot."""
    entry = _PREP_CACHE.pop(id(data_nd), None)
    if entry is None or entry[0] is not data_nd:
        return None
    return entry[1]


def clear_stash():
    """Drop every stashed prep.  Called on pipeline restore / prefetcher
    teardown: a pre-crash batch's prep must never pair with a
    post-restore batch (the strong refs would also pin the dead epoch's
    batches in memory)."""
    _PREP_CACHE.clear()


# -- capture-trace plumbing ----------------------------------------------------
#
# While gluon/captured.py traces a sparse step it maps each table
# param's id to the microbatch's inverse-index tracer; ShardedEmbedding
# .hybrid_forward switches on the entry's presence, so the SAME block
# hybridizes into a plain CachedOp (dense gather) when no captured
# sparse trace is active.

_SCOPE = {}


@contextmanager
def capture_scope(mapping):
    saved = dict(_SCOPE)
    _SCOPE.update(mapping)
    try:
        yield
    finally:
        _SCOPE.clear()
        _SCOPE.update(saved)


def scope_entry(param_id):
    return _SCOPE.get(param_id)


def rows_lookup(rows, inv, out_shape):
    """In-program lookup over pre-gathered unique rows, with the eager
    sparse op's EXACT backward math.

    Forward: ``take(rows, inv)`` — composed with the pre-gather
    ``take(table, uniq)`` this reads bit-identical elements to the
    eager ``take(table, clipped_ids)`` (pure data movement).  Backward
    (custom_vjp, `jax.ops.segment_sum`): cotangents coalesce per unique
    row in float32 and cast back to the table dtype — operand-for-
    operand the eager op's backward, with `_cut` barriers where the
    eager tape materializes arrays (the incoming cotangent, the
    coalesced values, the lookup output), so XLA's fusion/contraction
    decisions partition exactly like the eager dispatch chain.  Padded
    bucket slots are segments no ``inv`` entry targets: their gradient
    rows are exact zeros."""
    import jax
    import jax.numpy as jnp

    from ..gluon.captured import _cut_fn

    cut = _cut_fn()
    n_rows = rows.shape[0]
    dtype = rows.dtype

    @jax.custom_vjp
    def lookup(r):
        return jnp.take(r, inv, axis=0)

    def _fwd(r):
        return jnp.take(r, inv, axis=0), None

    def _bwd(_res, ct):
        ct = cut(ct)
        vals = jax.ops.segment_sum(ct.astype(jnp.float32), inv,
                                   num_segments=n_rows)
        return (cut(vals.astype(dtype)),)

    lookup.defvjp(_fwd, _bwd)
    return cut(lookup(rows)).reshape(out_shape)
