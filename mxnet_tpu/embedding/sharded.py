"""ShardedEmbedding — the row-sharded, capture-eligible embedding table."""

from __future__ import annotations

from ..gluon.block import HybridBlock
from . import prep as _prep


class ShardedEmbedding(HybridBlock):
    """Index → vector lookup whose table row-shards over the mesh and
    whose sparse gradient runs INSIDE the captured train step.

    The table parameter is named ``embed_table`` — not ``*_weight`` —
    so the `parallel.sharding.EmbeddingRules` overlay claims its row
    (vocab) dim for the dp/fsdp axis without colliding with
    TRANSFORMER_TP_RULES' column-parallel ``embedding\\d*_weight`` rule;
    an explicit user rule on the output dim merges per-dim (PR 17).

    Three forward modes, switched per trace:

    - captured (gluon/captured.py active, `prep.capture_scope` holds
      this table's inverse-index tracer): ``embed_table`` arrives as
      the program's pre-gathered ``(bucket, dim)`` unique rows and the
      lookup is `prep.rows_lookup` — gather by inverse index forward,
      `segment_sum` coalesce backward, bitwise-equal to the eager op;
    - eager tape: the compact `ops.indexing.sparse_embedding` op
      (O(touched rows) gradient) — the parity oracle;
    - plain jit / symbol / sparse_grad=False: the dense ``F.Embedding``
      gather, whose scatter-add transpose is already the fused row
      update under jit.

    ``feature`` selects the id column(s) from the LAST axis of the
    input (an int or a slice), for recommender batches that carry the
    categorical fields inside one dense feature tensor; None means the
    input IS the id tensor.  Under capture, the host id-prep applies
    the same selector to the same batch — the block must consume the
    step's ``data`` (or its feature slice) directly.
    """

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=True, feature=None,
                 **kwargs):
        super().__init__(**kwargs)
        self._input_dim = int(input_dim)
        self._output_dim = int(output_dim)
        self._sparse_grad = bool(sparse_grad)
        if feature is not None and not isinstance(feature, (int, slice)):
            raise TypeError(
                "ShardedEmbedding: feature must be None, an int, or a "
                f"slice of the last input axis, got {type(feature)}")
        self._feature = feature
        self._kwargs = {"input_dim": int(input_dim),
                        "output_dim": int(output_dim)}
        with self.name_scope():
            # registered under the attribute name ``embed_table`` so the
            # hybrid_forward kwarg and the parameter name agree
            self.embed_table = self.params.get(
                "embed_table", shape=(input_dim, output_dim),
                init=weight_initializer, dtype=dtype,
                allow_deferred_init=True,
                grad_stype="row_sparse" if sparse_grad else "default")

    @property
    def weight(self):
        """Alias matching ``gluon.nn.Embedding.weight``."""
        return self.embed_table

    def _ids_shape(self, x):
        shp = tuple(x.shape)
        if self._feature is None:
            return shp
        if isinstance(self._feature, slice):
            start, stop, step = self._feature.indices(int(shp[-1]))
            return shp[:-1] + (len(range(start, stop, step)),)
        return shp[:-1]

    def hybrid_forward(self, F, x, embed_table):
        from ..autograd import is_recording
        from ..ndarray.ndarray import NDArray, _from_jax

        inv = _prep.scope_entry(id(self.weight))
        if inv is not None:
            # captured trace: embed_table is the pre-gathered unique
            # rows; ids already folded into inv on the host
            out_shape = self._ids_shape(x) + (self._output_dim,)
            return _prep.rows_lookup(embed_table, inv, out_shape)
        from ..symbol import Symbol as _Symbol

        if isinstance(x, _Symbol):
            if self._feature is not None:
                raise NotImplementedError(
                    "ShardedEmbedding feature selection has no symbolic "
                    "path — export the surrounding block with "
                    "feature=None inputs")
            return F.Embedding(x, embed_table, **self._kwargs)
        if self._sparse_grad and isinstance(x, NDArray) \
                and isinstance(embed_table, NDArray) and is_recording():
            from ..ops.indexing import sparse_embedding

            ids = x if self._feature is None \
                else _from_jax(x._data[..., self._feature])
            return sparse_embedding(ids, embed_table)
        if self._feature is None:
            xx = x
        elif isinstance(x, NDArray):
            xx = _from_jax(x._data[..., self._feature])
        else:
            xx = x[..., self._feature]
        return F.Embedding(xx, embed_table, **self._kwargs)

    def __repr__(self):
        return "{name}({i} -> {o}, {dt}{sp})".format(
            name=self.__class__.__name__, i=self._input_dim,
            o=self._output_dim, dt=self.weight.dtype,
            sp=", sparse_grad" if self._sparse_grad else "")
