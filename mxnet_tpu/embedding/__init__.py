"""Sharded embedding tables on the captured-step path (the recommender
workload).

Embedding(sparse_grad=True) has been an eager-only configuration since
the sparse milestone: the compact row-sparse gradient lives on the eager
tape, so whole-step capture (gluon/captured.py) declined it and the one
workload that most stresses "millions of users" ran multi-dispatch.
This package promotes the sparse path INTO the donated program:

- `ShardedEmbedding` — a Gluon block whose table parameter is named
  ``embed_table`` so the `EmbeddingRules` overlay
  (parallel/sharding.py) row-shards it over the dp/fsdp mesh axis,
  composable with TP/PP via the per-dim merge.  Inside a captured trace
  the lookup becomes gather(gathered-unique-rows, inverse-index); on
  the eager tape it stays the compact `sparse_embedding` op — the
  bitwise parity oracle.
- host-side id prep (`prep.prepare_step`): unique ids + inverse index
  computed on the host (or ahead of time on the DevicePrefetcher's
  producer thread), padded to a power-of-two unique-count bucket that
  joins the capture key, so retraces are bounded by the number of
  distinct buckets and the step keeps exactly one dispatch + one
  readback.
- the row-sparse update itself runs through
  `optimizer.grouped.sparse_row_kernel` — the same fused SGD/Adam
  kernels on just the gathered rows, shared by the eager grouped path
  and the captured program (PR 6 bitwise-oracle discipline).

``MXTPU_SPARSE_CAPTURED=0`` pins sparse configs to the eager oracle;
any forced fallback (dist kvstore, indivisible bucket, foreign
optimizer) emits a ``sparse_fallback{reason}`` telemetry event rather
than degrading silently.
"""

from .prep import (SparsePrep, bucket_for, capture_scope,
                   find_sparse_embeddings, pop_prep, prepare_step,
                   rows_lookup, scope_entry, sparse_capture_reason,
                   sparse_captured_enabled, stash_prep,
                   unique_bucket_env)
from .sharded import ShardedEmbedding

__all__ = [
    "ShardedEmbedding", "SparsePrep", "bucket_for", "capture_scope",
    "find_sparse_embeddings", "pop_prep", "prepare_step", "rows_lookup",
    "scope_entry", "sparse_capture_reason", "sparse_captured_enabled",
    "stash_prep", "unique_bucket_env",
]
