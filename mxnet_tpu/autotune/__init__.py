"""Self-tuning performance layer: measure → search → persist → replay.

The repo accumulated a real knob space (all-reduce bucket MB, FSDP min
size, prefetch depth, shm slot MB, remat policy, optimizer group
splitting, grad-accum) and telemetry built the objective function
(StepStats wall time + MFU).  This package closes the loop:

- `space`  — typed knob declarations with domains, layers, and the
  numerics-safety flag (the search touches semantics-changing knobs
  only behind ``MXTPU_TUNE_SEMANTICS=1``).
- `runner` — scores one candidate on the live trainer through the
  normal capture path; OOM = infeasible point, trial steps are marked
  in telemetry.
- `search` — successive-halving local search, ``MXTPU_TUNE_BUDGET``
  trials per capture signature.
- `db`     — crash-safe CRC'd JSONL next to the XLA compile cache,
  keyed by (capture signature, device kind, mesh shape).

`Trainer.train_step` calls `maybe_tune` once per capture signature:
``MXTPU_AUTOTUNE=replay`` (default) applies a stored winner with zero
trials, ``search`` searches when the DB has no entry and persists the
winner, ``off`` does nothing.
"""

from __future__ import annotations

import hashlib
import json
import os

from .. import telemetry
from . import db, runner, search, space  # noqa: F401  (public submodules)

#: guards re-entry: trial steps call Trainer.train_step, which calls
#: maybe_tune again.
_IN_PROGRESS = False


def device_kind():
    import jax

    try:
        return jax.devices()[0].device_kind
    except Exception:
        return "unknown"


def _mesh_shape(trainer):
    from ..parallel.sharding import mesh_of_params

    try:
        mesh = mesh_of_params(list(trainer._params))
    except Exception:
        mesh = None
    if mesh is None:
        return None
    return tuple(sorted(mesh.shape.items()))


def _norm_name(name):
    """Strip the per-process block-name counters ('dense3_weight' →
    'dense_weight'): a restarted process must hash to the same
    signature for the same model."""
    import re

    return re.sub(r"\d+", "", str(name))


def signature_of(trainer, block, loss_fn, data, grad_accum):
    """Stable per-process-independent capture signature: what model,
    what parameters, what optimizer, what batch — the same identity
    the capture cache keys on, minus object ids (a DB entry must
    survive restarts)."""
    params = []
    for p in trainer._params:
        params.append((_norm_name(getattr(p, "name", "")),
                       tuple(getattr(p, "shape", ()) or ()),
                       str(getattr(p, "dtype", "")),
                       getattr(p, "_grad_req", "write")))
    blob = json.dumps({
        "block": type(block).__name__,
        "loss": type(loss_fn).__name__,
        "optimizer": type(trainer._optimizer).__name__,
        "params": params,
        "batch": [tuple(data.shape), str(data.dtype)],
        "grad_accum": int(grad_accum),
    }, sort_keys=True, default=str, separators=(",", ":"))
    return hashlib.sha1(blob.encode()).hexdigest()[:16]


def effective_grad_accum(k, data):
    """The semantics-changing grad-accum override: honored only behind
    the MXTPU_TUNE_SEMANTICS opt-in, and only when it divides the
    batch."""
    if not space.semantics_opt_in():
        return k
    raw = os.environ.get("MXTPU_GRAD_ACCUM")
    if not raw:
        return k
    try:
        ka = int(raw)
    except ValueError:
        return k
    if ka >= 1 and data.shape[0] % ka == 0:
        return ka
    return k


def maybe_tune(trainer, block, loss_fn, data, label, grad_accum):
    """Trainer.train_step hook.  Consults the tuning DB once per
    (signature, device kind, mesh) on this trainer — replaying a stored
    winner or (mode=search) running the successive-halving search on
    the live trainer — then returns the effective grad-accum factor."""
    global _IN_PROGRESS
    mode = search.mode()
    if mode == "off" or _IN_PROGRESS:
        # trial steps still honor the candidate's grad-accum env
        return effective_grad_accum(int(grad_accum), data)
    # per-step fast path: the full signature hashes every param — only
    # compute it the first time this cheap call shape appears
    cheap = (id(block), id(loss_fn), tuple(data.shape),
             str(data.dtype), int(grad_accum), mode)
    seen = getattr(trainer, "_autotune_seen", None)
    if seen is None:
        seen = trainer._autotune_seen = set()
    if cheap not in seen:
        seen.add(cheap)
        key = db.entry_key(
            signature_of(trainer, block, loss_fn, data, grad_accum),
            device_kind(), _mesh_shape(trainer))
        entry = db.lookup(key)
        if entry is not None:
            space.apply_config(entry["config"])
            telemetry.event("tune_db_hit", key=key,
                            fingerprint=entry.get("fingerprint"),
                            score_us=entry.get("score_us"))
        elif mode == "search":
            _search_and_apply(trainer, block, loss_fn, data, label,
                              int(grad_accum), key)
    return effective_grad_accum(int(grad_accum), data)


def _search_and_apply(trainer, block, loss_fn, data, label,
                      grad_accum, key):
    """Run the search on the live trainer (trial steps DO advance the
    weights — tuning is part of warmup), apply + persist the winner."""
    global _IN_PROGRESS
    base = space.current_config()
    base_fp = space.fingerprint(base)

    def step_fn():
        trainer.train_step(block, loss_fn, data, label=label,
                           grad_accum=grad_accum)

    _IN_PROGRESS = True
    try:
        winner, results = search.successive_halving(step_fn, base=base)
    finally:
        _IN_PROGRESS = False
    if not winner.feasible:
        # every candidate OOM'd (shouldn't happen: base was running
        # before the search) — keep defaults, record nothing
        return
    base_scores = [r.score_us for r in results
                   if r.fingerprint == base_fp and r.feasible]
    default_score = min(base_scores) if base_scores else None
    space.apply_config(winner.config)
    db.record(key, winner.config, winner.score_us, mfu=winner.mfu,
              trials=len(results), default_score_us=default_score)
    improvement = (default_score / winner.score_us) \
        if default_score else None
    telemetry.event(
        "tune_winner", key=key, fingerprint=winner.fingerprint,
        score_us=round(winner.score_us, 1),
        default_score_us=None if default_score is None
        else round(default_score, 1),
        improvement=None if improvement is None
        else round(improvement, 4),
        trials=len(results))


def sharded_signature(sharded_trainer, example):
    """The ShardedTrainer analogue of `signature_of` (different attr
    layout: explicit trainable list, pure optimizer, grad_accum)."""
    import jax.tree_util as jtu

    st = sharded_trainer
    params = [(_norm_name(n), tuple(getattr(p, "shape", ()) or ()),
               str(getattr(p, "dtype", "")))
              for n, p in getattr(st, "_trainable", [])]
    shapes = [(tuple(x.shape), str(x.dtype))
              for x in jtu.tree_leaves(example)]
    blob = json.dumps({
        "block": type(st.block).__name__,
        "loss": type(st.loss_fn).__name__,
        "optimizer": st.optimizer.name,
        "params": params,
        "batch": shapes,
        "grad_accum": int(st._grad_accum),
    }, sort_keys=True, default=str, separators=(",", ":"))
    return hashlib.sha1(blob.encode()).hexdigest()[:16]


def replay_for_sharded(signature, mesh):
    """ShardedTrainer's capture-time DB consult: replay-only (the
    sharded step path has its own build flow; searching it re-enters
    compilation too deeply for a trial loop to pay off on-mesh).
    Returns the applied entry or None."""
    if search.mode() == "off":
        return None
    mesh_shape = None if mesh is None \
        else tuple(sorted(mesh.shape.items()))
    key = db.entry_key(signature, device_kind(), mesh_shape)
    entry = db.lookup(key)
    if entry is not None:
        space.apply_config(entry["config"])
        telemetry.event("tune_db_hit", key=key,
                        fingerprint=entry.get("fingerprint"),
                        score_us=entry.get("score_us"))
    return entry
