"""Typed knob-space declaration for the autotuner.

Every tunable the repo has accumulated is registered here with its env
var, value domain, the layer it acts on, and — the safety model — a
``numerics_preserving`` flag.  Numerics-preserving knobs change HOW the
same math runs (bucketing, sharding thresholds, prefetch depth, remat
recompute, optimizer group splitting) and are searchable by default;
semantics-changing knobs (grad-accum factor: different update math for
the same global batch) are searched ONLY behind the explicit
``MXTPU_TUNE_SEMANTICS=1`` opt-in and never silently replayed.

Knob values are env-var strings: applying a config IS setting env vars,
which the consuming modules (kvstore._bucket_bytes,
sharding.fsdp_min_size, the prefetcher, grouped.group_max_items,
remat.env_default) already re-read at use time — runtime re-application
needs no plumbing.  Program-affecting knobs (layer 'program') change
the traced step program; gluon/captured.py folds their fingerprint into
the capture cache key so flipping one re-captures instead of silently
reusing a stale program.
"""

from __future__ import annotations

import hashlib
import json
import os

from ..base import MXNetError


class Knob:
    """One registered tunable: an env-backed value with a finite search
    domain."""

    __slots__ = ("name", "env", "domain", "default", "layer",
                 "numerics_preserving", "doc")

    def __init__(self, name, env, domain, default, layer,
                 numerics_preserving=True, doc=""):
        assert default in domain, (name, default, domain)
        self.name = name
        self.env = env
        self.domain = tuple(str(v) for v in domain)
        self.default = str(default)
        self.layer = layer
        self.numerics_preserving = bool(numerics_preserving)
        self.doc = doc

    def current(self):
        """The active value: env var if set (and in-domain values only
        normalize trivially — out-of-domain env values pass through so
        hand-set configs are honored), else the default."""
        raw = os.environ.get(self.env)
        return raw if raw not in (None, "") else self.default

    def validate(self, value):
        if str(value) not in self.domain:
            raise MXNetError(
                f"knob {self.name}: value {value!r} not in domain "
                f"{self.domain}")
        return str(value)

    def neighbors(self, value):
        """Domain values adjacent to ``value`` (local-search moves).
        Out-of-domain current values get the whole domain as
        neighborhood."""
        value = str(value)
        if value not in self.domain:
            return list(self.domain)
        i = self.domain.index(value)
        out = []
        if i > 0:
            out.append(self.domain[i - 1])
        if i + 1 < len(self.domain):
            out.append(self.domain[i + 1])
        return out


#: name -> Knob, declaration order = reporting order.
KNOBS = {}


def register(knob):
    KNOBS[knob.name] = knob
    return knob


register(Knob(
    "allreduce_bucket_mb", "MXTPU_ALLREDUCE_BUCKET_MB",
    ("1", "2", "4", "8", "16"), "4", layer="collective",
    doc="gradient all-reduce bucket budget (kvstore.bucketed_pushpull)"))
register(Knob(
    "fsdp_min_size", "MXTPU_FSDP_MIN_SIZE",
    ("256", "1024", "4096", "16384"), "1024", layer="sharding",
    doc="smallest param FSDPRules will shard (parallel/sharding.py)"))
register(Knob(
    "device_prefetch", "MXTPU_DEVICE_PREFETCH",
    ("0", "1", "2", "4"), "2", layer="input",
    doc="device-prefetch queue depth (gluon/data/prefetcher.py)"))
register(Knob(
    "shm_slot_mb", "MXTPU_SHM_SLOT_MB",
    ("8", "16", "32", "64"), "32", layer="input",
    doc="shared-memory slot size of the worker dataloader"))
register(Knob(
    "remat", "MXTPU_REMAT",
    ("none", "dots", "full", "save_every_k:2"), "none",
    layer="program",
    doc="rematerialization policy (remat.py registry; bitwise-safe)"))
register(Knob(
    "group_max_items", "MXTPU_GROUP_MAX_ITEMS",
    ("0", "8", "32"), "0", layer="program",
    doc="max params fused per optimizer group, 0 = unlimited "
        "(optimizer/grouped.plan_items)"))
register(Knob(
    "pp_microbatches", "MXTPU_PP_MICROBATCHES",
    ("0", "1", "2", "4", "8"), "0", layer="program",
    numerics_preserving=False,
    doc="microbatches per pipeline stage-pass in the captured 1F1B "
        "schedule, 0 = auto (the mesh's pp size); program-affecting — "
        "folded into the capture-cache key (gluon/captured.py).  Like "
        "grad_accum it CHANGES update math for the same global batch "
        "(captured(k, m) matches the eager oracle at grad_accum=k*m), "
        "so the search touches it only with MXTPU_TUNE_SEMANTICS=1"))
register(Knob(
    "unique_bucket", "MXTPU_UNIQUE_BUCKET",
    ("0", "256", "1024", "4096"), "0", layer="program",
    doc="fixed unique-id bucket for captured sparse-embedding steps, "
        "0 = auto (next power of two per batch); program-affecting — "
        "the bucket is the padded gather width and joins the capture "
        "key (embedding/prep.py, gluon/captured.py).  A fixed bucket "
        "trades one capture signature for padding waste; a batch whose "
        "unique count exceeds it falls back to the eager oracle with a "
        "sparse_fallback telemetry event.  Bitwise-neutral: padded "
        "rows never reach the table"))
register(Knob(
    "grad_accum", "MXTPU_GRAD_ACCUM",
    ("1", "2", "4"), "1", layer="schedule",
    numerics_preserving=False,
    doc="grad-accum factor override — CHANGES update math for the same "
        "global batch; searched only with MXTPU_TUNE_SEMANTICS=1"))


def semantics_opt_in():
    """MXTPU_TUNE_SEMANTICS gate (default off): allow the search to
    touch semantics-changing knobs."""
    return os.environ.get("MXTPU_TUNE_SEMANTICS", "0").lower() \
        not in ("0", "false", "off", "")


def searchable_knobs(include_semantics_changing=None):
    """The knobs the search driver may move, in declaration order."""
    if include_semantics_changing is None:
        include_semantics_changing = semantics_opt_in()
    return [k for k in KNOBS.values()
            if k.numerics_preserving or include_semantics_changing]


def default_config():
    return {k.name: k.default for k in KNOBS.values()}


def current_config():
    """The active config as {knob name: value string} (env or default
    per knob)."""
    return {k.name: k.current() for k in KNOBS.values()}


def apply_config(config):
    """Set each knob's env var from ``config`` (missing knobs reset to
    default) and stamp the fingerprint into telemetry.  Returns the
    previous env values for `restore_env`.  The consuming modules
    re-read env at use time, so this IS the runtime re-application."""
    from .. import telemetry

    prev = {}
    opt_in = semantics_opt_in()
    for knob in KNOBS.values():
        if not knob.numerics_preserving and not opt_in:
            # a semantics-changing value is never applied silently —
            # not even from a stored DB entry
            continue
        prev[knob.env] = os.environ.get(knob.env)
        os.environ[knob.env] = str(config.get(knob.name, knob.default))
    telemetry.set_config_fingerprint(fingerprint(current_config()))
    return prev


def restore_env(prev):
    """Undo `apply_config` (trial cleanup)."""
    from .. import telemetry

    for env, old in prev.items():
        if old is None:
            os.environ.pop(env, None)
        else:
            os.environ[env] = old
    telemetry.set_config_fingerprint(None)


def fingerprint(config):
    """Stable 12-hex digest of a config dict — the telemetry
    ``config_fingerprint`` field and the tuning-DB entry id."""
    blob = json.dumps({k: str(v) for k, v in sorted(config.items())},
                      sort_keys=True, separators=(",", ":"))
    return hashlib.sha1(blob.encode()).hexdigest()[:12]


def program_knob_values():
    """(name, value) of layer='program' knobs — the part of the active
    config that changes the traced step program.  gluon/captured.py
    folds this into the capture cache key."""
    return tuple((k.name, k.current()) for k in KNOBS.values()
                 if k.layer == "program")
