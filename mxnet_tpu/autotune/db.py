"""Crash-safe tuning database: CRC'd JSONL + atomic rename.

Same durability discipline as the checkpoint manifest
(mxnet_tpu/checkpoint.py): every entry line carries a CRC32 of its
payload, rewrites go through ``tmp file → fsync → os.replace → dir
fsync`` so the commit point is a single atomic rename, and readers
treat ANY malformed line — torn tail from a crash mid-write, bit-rot,
stale schema — as absent-with-a-logged-event (``tune_db_fallback``),
never as a crash.  Stale-version entries are GC'd on the next write.

Location: ``MXTPU_TUNE_DB`` when set, else ``tune_db.jsonl`` next to
the persistent XLA compile cache (``MXTPU_COMPILE_CACHE_DIR``) — the
two caches answer the same question ("have I seen this program
before?") and travel together across restarts.  Neither set → no
persistence (search still runs, winners just aren't replayable).

Entries are keyed by (capture signature, device kind, mesh shape): a
config tuned on the CPU test mesh never replays on a TPU slice, and a
re-sharded model re-tunes.
"""

from __future__ import annotations

import json
import os
import zlib

DB_VERSION = 1


def tune_db_path():
    """The database file path, or None when persistence is off."""
    p = os.environ.get("MXTPU_TUNE_DB")
    if p:
        return p
    cache = os.environ.get("MXTPU_COMPILE_CACHE_DIR")
    if cache:
        return os.path.join(cache, "tune_db.jsonl")
    return None


def entry_key(signature, device_kind, mesh_shape):
    """The DB key string.  ``signature`` is the trainer's stable
    capture signature, ``mesh_shape`` a ((axis, size), ...) tuple or
    None."""
    mesh = "x".join(f"{a}={n}" for a, n in (mesh_shape or ()))
    return f"{signature}|{device_kind}|{mesh or 'single'}"


def _encode(entry):
    """One JSONL line: the payload json plus a trailing CRC32 of the
    payload bytes (the checkpoint-manifest discipline, readable by eye
    and by `zlib.crc32`)."""
    payload = json.dumps(entry, sort_keys=True, separators=(",", ":"))
    crc = zlib.crc32(payload.encode()) & 0xFFFFFFFF
    return json.dumps({"crc": crc, "payload": payload},
                      separators=(",", ":")) + "\n"


def _decode(line):
    """The entry dict, or None for any malformed/torn/corrupt line."""
    try:
        outer = json.loads(line)
        payload = outer["payload"]
        if zlib.crc32(payload.encode()) & 0xFFFFFFFF != outer["crc"]:
            return None
        entry = json.loads(payload)
        return entry if isinstance(entry, dict) else None
    except (ValueError, KeyError, TypeError):
        return None


def load(path=None):
    """{key: entry} of every valid current-version entry (later lines
    win).  Corrupt/torn lines and stale-version entries are skipped
    with ONE ``tune_db_fallback`` telemetry event per load — the run
    continues at defaults, it never crashes on its own database."""
    from .. import telemetry

    path = path or tune_db_path()
    entries = {}
    bad = stale = 0
    if path is None or not os.path.exists(path):
        return entries
    try:
        with open(path, encoding="utf-8") as f:
            lines = f.readlines()
    except OSError:
        telemetry.event("tune_db_fallback", reason="unreadable",
                        path=path)
        return entries
    for line in lines:
        if not line.strip():
            continue
        entry = _decode(line)
        if entry is None:
            bad += 1
            continue
        if entry.get("db_version") != DB_VERSION:
            stale += 1
            continue
        key = entry.get("key")
        if key:
            entries[key] = entry
    if bad or stale:
        telemetry.event("tune_db_fallback", path=path,
                        corrupt_entries=bad, stale_entries=stale)
    return entries


def lookup(key, path=None):
    """The stored entry for ``key``, or None."""
    return load(path).get(key)


def record(key, config, score_us, path=None, mfu=None, trials=None,
           default_score_us=None):
    """Upsert the winning ``config`` for ``key`` and atomically rewrite
    the database.  The rewrite GCs corrupt and stale-version entries as
    a side effect (they simply aren't carried over).  Returns the
    entry, or None when persistence is off."""
    import time

    from .. import resilience, telemetry
    from . import space

    path = path or tune_db_path()
    if path is None:
        return None
    entries = load(path)
    entry = {
        "db_version": DB_VERSION,
        "key": key,
        "config": {k: str(v) for k, v in config.items()},
        "fingerprint": space.fingerprint(config),
        "score_us": float(score_us),
        "t": time.time(),
    }
    if mfu is not None:
        entry["mfu"] = float(mfu)
    if trials is not None:
        entry["trials"] = int(trials)
    if default_score_us is not None:
        entry["default_score_us"] = float(default_score_us)
    entries[key] = entry
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        for k in sorted(entries):
            line = _encode(entries[k])
            if k == key and resilience.consume_fault("corrupt_tune_db"):
                # injected bit-rot: flip a byte mid-payload so the CRC
                # check must catch it on the next load
                mid = len(line) // 2
                line = line[:mid] + ("X" if line[mid] != "X" else "Y") \
                    + line[mid + 1:]
            f.write(line)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    try:
        dfd = os.open(d, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:
        pass
    telemetry.event("tune_db_write", key=key,
                    fingerprint=entry["fingerprint"],
                    score_us=entry["score_us"])
    return entry
