"""Trial runner: score one candidate config on the live trainer.

A trial applies the candidate via `space.apply_config` (env vars — the
runtime re-application path every knob consumer already re-reads),
re-enters the trainer's normal step path — program-affecting knobs
miss the capture cache and re-capture through the SAME
`gluon/captured.py` machinery as production steps — and times K warm
steps via the telemetry StepStats records those steps emit.  Every
trial step is stamped ``tuning_trial`` (telemetry.trial_begin), so
steady-state aggregates never see trial noise.

Infeasibility is a result, not a crash: a candidate that OOMs
(``RESOURCE_EXHAUSTED`` from the runtime, or the hermetic ``tune_oom``
fault injection) scores +inf and the search moves on.
"""

from __future__ import annotations

import math
import time

from .. import resilience, telemetry
from . import space


def trial_steps():
    """Warm steps timed per trial rung (MXTPU_TUNE_STEPS, default 3)."""
    from ..base import getenv_int

    return max(1, getenv_int("MXTPU_TUNE_STEPS", 3))


class SimulatedOOM(RuntimeError):
    """The tune_oom fault site's stand-in for an XLA allocator
    failure."""

    def __init__(self):
        super().__init__(
            "RESOURCE_EXHAUSTED: injected tune_oom (MXTPU_FAULT_INJECT)")


def is_resource_exhausted(exc) -> bool:
    """True when the exception is an out-of-memory allocator failure —
    the XLA runtime spells it RESOURCE_EXHAUSTED."""
    msg = str(exc)
    return "RESOURCE_EXHAUSTED" in msg or "Out of memory" in msg \
        or "out of memory" in msg


class TrialResult:
    """Outcome of one trial: feasible-with-score or infeasible."""

    __slots__ = ("config", "fingerprint", "feasible", "score_us",
                 "mfu", "steps", "error")

    def __init__(self, config, fingerprint, feasible, score_us,
                 mfu=None, steps=0, error=None):
        self.config = config
        self.fingerprint = fingerprint
        self.feasible = feasible
        self.score_us = score_us        # mean step wall time; inf = infeasible
        self.mfu = mfu
        self.steps = steps
        self.error = error

    def __repr__(self):
        state = f"{self.score_us:.0f}us" if self.feasible else "infeasible"
        return f"TrialResult({self.fingerprint}, {state})"


def run_trial(step_fn, config, steps=None, warmup=1):
    """Apply ``config``, run ``warmup`` untimed + ``steps`` timed steps
    through ``step_fn`` (one full training step per call), and score by
    mean StepStats wall_us.  The env is restored afterwards — the
    search driver, not the trial, decides what sticks."""
    steps = steps or trial_steps()
    fp = space.fingerprint(config)
    prev = space.apply_config(config)
    telemetry.trial_begin(fp)
    t0 = time.perf_counter()
    ran = 0
    try:
        if resilience.consume_fault("tune_oom"):
            raise SimulatedOOM()
        for _ in range(warmup):
            step_fn()
        t0 = time.perf_counter()
        for _ in range(steps):
            step_fn()
            ran += 1
    except Exception as e:
        if is_resource_exhausted(e):
            telemetry.event("tune_infeasible", fingerprint=fp,
                            error=str(e)[:200])
            return TrialResult(config, fp, feasible=False,
                               score_us=math.inf, error=str(e))
        raise
    finally:
        telemetry.trial_end()
        space.restore_env(prev)
    elapsed_us = (time.perf_counter() - t0) * 1e6
    recs = [r for r in telemetry.recent_steps(include_trials=True)
            if r.get("tuning_trial")
            and r.get("config_fingerprint") == fp]
    recs = recs[-ran:] if ran else []
    if recs:
        score = sum(r["wall_us"] for r in recs) / len(recs)
        mfus = [r["mfu"] for r in recs if r.get("mfu") is not None]
        mfu = sum(mfus) / len(mfus) if mfus else None
    else:                       # telemetry off: raw wall clock
        score = elapsed_us / max(ran, 1)
        mfu = None
    telemetry.event("tune_trial", fingerprint=fp, steps=ran,
                    score_us=round(score, 1))
    return TrialResult(config, fp, feasible=True, score_us=score,
                       mfu=mfu, steps=ran)
