"""Successive-halving local search over the knob space.

The candidate pool is the base config plus every one-knob move into an
adjacent domain value (local search: the space declaration orders each
domain, so "adjacent" is meaningful).  Rungs evaluate every surviving
candidate for a small number of warm steps, keep the faster half, and
double the steps — cheap configs are rejected on little evidence,
promising ones earn longer measurement (the Hyperband/ASHA shape,
simplified to one bracket).  The whole search spends at most
``MXTPU_TUNE_BUDGET`` trials per capture signature; the base config is
always a candidate, so the winner is never slower than the defaults
*as measured* — and the driver double-checks by falling back to base
when the winner's final score doesn't beat it.
"""

from __future__ import annotations

import math
import os

from .. import telemetry
from . import runner as _runner
from . import space

_MODES = ("off", "replay", "search")


def mode():
    """MXTPU_AUTOTUNE: 'off' (never touch the DB), 'replay' (apply a
    stored winner, never search — the default), or 'search' (search
    when the DB has no entry, then persist)."""
    m = os.environ.get("MXTPU_AUTOTUNE", "replay").lower() or "replay"
    if m in ("0", "false"):
        m = "off"
    if m not in _MODES:
        from ..base import MXNetError

        raise MXNetError(
            f"MXTPU_AUTOTUNE={m!r}: expected one of {_MODES}")
    return m


def budget():
    """MXTPU_TUNE_BUDGET: max trials per capture signature (default
    12)."""
    from ..base import getenv_int

    return max(1, getenv_int("MXTPU_TUNE_BUDGET", 12))


def candidates(base=None, knobs=None):
    """Base + every one-knob adjacent move (deduped, base first)."""
    base = dict(base if base is not None else space.current_config())
    knobs = knobs if knobs is not None else space.searchable_knobs()
    out = [base]
    seen = {space.fingerprint(base)}
    for knob in knobs:
        for v in knob.neighbors(base.get(knob.name, knob.default)):
            cfg = dict(base)
            cfg[knob.name] = v
            fp = space.fingerprint(cfg)
            if fp not in seen:
                seen.add(fp)
                out.append(cfg)
    return out


def successive_halving(step_fn, base=None, knobs=None,
                       total_budget=None, rung_steps=None):
    """Run the search; returns (winner TrialResult, all TrialResults).

    The returned winner is the best FEASIBLE result (ties break toward
    the base config); when every candidate is infeasible — or the
    budget is 0 trials — the base config wins at +inf so the caller
    simply keeps defaults."""
    total_budget = total_budget if total_budget is not None else budget()
    rung_steps = rung_steps or _runner.trial_steps()
    pool = candidates(base, knobs)
    base_fp = space.fingerprint(pool[0])
    telemetry.event("tune_search_start", candidates=len(pool),
                    budget=total_budget)
    all_results = []
    best = {}                     # fingerprint -> best TrialResult
    spent = 0
    steps = rung_steps
    while pool and spent < total_budget:
        scored = []
        for cfg in pool:
            if spent >= total_budget:
                break
            res = _runner.run_trial(step_fn, cfg, steps=steps)
            spent += 1
            all_results.append(res)
            scored.append(res)
            prev = best.get(res.fingerprint)
            if prev is None or res.score_us < prev.score_us:
                best[res.fingerprint] = res
        if len(scored) <= 1:
            break
        scored.sort(key=lambda r: (r.score_us,
                                   r.fingerprint != base_fp))
        keep = max(1, math.ceil(len(scored) / 2))
        pool = [r.config for r in scored[:keep] if r.feasible]
        if len(pool) <= 1:
            break
        steps *= 2
    feasible = [r for r in best.values() if r.feasible]
    if feasible:
        winner = min(feasible,
                     key=lambda r: (r.score_us, r.fingerprint != base_fp))
    else:
        winner = _runner.TrialResult(dict(pool[0]) if pool else
                                     dict(candidates(base, knobs)[0]),
                                     base_fp, feasible=False,
                                     score_us=math.inf)
    base_res = best.get(base_fp)
    if base_res is not None and base_res.feasible \
            and base_res.score_us < winner.score_us:
        winner = base_res
    return winner, all_results
