"""Always-on training telemetry: metrics registry, per-step StepStats,
MFU accounting, and a crash-safe JSONL event log.

NEW, TPU-first (no reference analog — the reference's profiler is
opt-in and throws its data away between runs).  Once the whole step
collapses into one compiled program (gluon/captured.py), *attribution*
— knowing whether wall time went to data staging, host prep, dispatch,
collectives, or the guard readback — is the only way to find the next
bottleneck (PyGraph / XLA-fusion papers, PAPERS.md).  This module keeps
that attribution, always, at <1% of step time:

- `MetricsRegistry` — process-wide counters / gauges / time-and-byte
  histograms.  Components increment (`count`, `gauge_set`, `observe`);
  the per-step assembler reads deltas.  No device work, ever.
- `StepStats` — ONE record per training step, assembled from the
  existing single host readback plus the `profiler.annotate` scope
  durations (forwarded here by the profiler's scope hook): step wall
  time, data-stall share, host prep, dispatch, guard readback,
  collective bytes/buckets, capture-cache hit, skipped-step flag, and
  MFU.  Breakdown shares (including ``other``) sum to 1.0 over the
  inter-step interval.
- MFU — FLOPs come from the compiled step program's own XLA cost
  analysis (`CapturedStep.cost_flops`, one lowering per capture
  signature, never per step), divided by the per-device-kind peak-FLOPs
  table below (`MXTPU_PEAK_FLOPS` overrides).
- Event log — append-only JSONL (`MXTPU_TELEMETRY_PATH`), one
  run-id-stamped record per step plus discrete events (skip-step,
  divergence rollback, watchdog expiry, restart, checkpoint commit).
  Writes are line-buffered and flushed per record; a crash mid-append
  leaves every earlier line parseable (readers skip a truncated tail —
  `tools/trace_report.py`).  Without a path, records land in a bounded
  in-memory ring (`recent_steps()`), which is how bench.py reads them.

Controlled by ``MXTPU_TELEMETRY`` (default on).  Zero extra device
dispatches or host readbacks: everything here is host timers and dict
assembly (pinned by tests/test_telemetry.py).
"""

from __future__ import annotations

import json
import os
import threading
import time

_LOCK = threading.Lock()

# v2 (autotune): step records gain optional ``tuning_trial`` (bool) and
# ``config_fingerprint`` (str) fields; v1 records stay valid.
# v3 (fleet observability): every record may carry ``rank`` / ``world``
# / ``replica_id`` identity fields, and request records may carry a
# ``trace_id`` plus a closed ``spans`` tree (obs/spans.py); v1/v2
# records stay valid.
# v4 (integrity plane): new ``integrity`` record type — one attestation
# round per record: {step, fp, ok} plus optional {epoch, peers,
# corrupt, kind}; v1/v2/v3 records stay valid.
# v5 (pipeline parallelism): step records may carry ``bubble_fraction``
# (the 1F1B schedule's idle share, in [0, 1)) next to mfu, and
# ``collective_bytes_by_axis`` may grow a ``pp`` row; v1–v4 records
# stay valid.
# v6 (sparse embeddings): step records may carry ``lookup_us`` (host
# id-prep time of a captured sparse step, microseconds, >= 0) and
# ``unique_fraction`` (unique ids / total ids, in (0, 1]); v1–v5
# records stay valid.
# v7 (resumable input pipeline): step records may carry
# ``samples_seen`` (global samples delivered to training so far, a
# non-negative int), and the event stream gains ``data_resume`` /
# ``batch_quarantined`` / ``data_worker_timeout`` kinds; v1–v6 records
# stay valid.
# v8 (split-brain fencing): step records may carry ``gang_epoch`` (the
# committed elastic-gang epoch the step ran under, a non-negative
# int), and the event stream gains ``fencing_rejected`` /
# ``ckpt_fenced`` / ``gang_fenced`` / ``partition_healed`` kinds;
# v1–v7 records stay valid.
SCHEMA_VERSION = 8
_ACCEPTED_VERSIONS = (1, 2, 3, 4, 5, 6, 7, 8)

# autotune trial marking (mxnet_tpu/autotune/runner.py): while a trial
# config is being timed every step record is stamped
# ``tuning_trial: true`` so steady-state consumers (recent_steps
# default, trace_report aggregates, bench) exclude it; outside trials
# an applied tuned config still stamps its fingerprint.
_TRIAL_FP = None
_CONFIG_FP = None


def trial_begin(config_fingerprint):
    """Mark subsequent step records as autotune trial steps."""
    global _TRIAL_FP
    _TRIAL_FP = str(config_fingerprint)


def trial_end():
    global _TRIAL_FP
    _TRIAL_FP = None


def set_config_fingerprint(config_fingerprint):
    """Stamp steady-state step records with the applied (tuned) config
    fingerprint; None clears."""
    global _CONFIG_FP
    _CONFIG_FP = None if config_fingerprint is None \
        else str(config_fingerprint)


# the committed elastic-gang epoch this process last adopted (schema
# v8); stamped onto step records so a post-hoc reader can tell which
# membership a step ran under — the forensic trail for fencing audits.
_GANG_EPOCH = None


def set_gang_epoch(epoch):
    """Stamp subsequent step records with the adopted gang epoch
    (schema v8 ``gang_epoch``); None clears."""
    global _GANG_EPOCH
    _GANG_EPOCH = None if epoch is None else int(epoch)

#: bf16 peak FLOP/s per chip by device-kind substring (public specs).
#: The ``cpu`` entry is a NOMINAL host figure so ratio gating works on
#: the CPU test mesh — CPU "MFU" is a relative gate, not a truth claim
#: (docs/observability.md).  ``MXTPU_PEAK_FLOPS`` overrides everything.
PEAK_FLOPS = [
    ("v6e", 918e12), ("v6", 918e12),
    ("v5p", 459e12), ("v5e", 197e12), ("v5 lite", 197e12),
    ("v4", 275e12), ("v3", 123e12), ("v2", 45e12),
    ("cpu", 2e11),
]

_BREAKDOWN_KEYS = ("data", "host_prep", "dispatch", "readback",
                   "collective", "other")

#: profiler.annotate scope name -> breakdown bucket.  ``h2d_prefetch``
#: is deliberately absent: it runs on the prefetcher's producer thread,
#: overlapped with compute, so adding it would double-count wall time
#: (it is reported separately via the ``input.wait_us`` counter).
_SCOPE_BUCKET = {
    "captured_data": "data",
    "captured_host_prep": "host_prep",
    "captured_step": "dispatch",
    "optimizer_update": "dispatch",
    "guard_readback": "readback",
    "allreduce": "collective",
    "bucket_pack": "collective",
}


def enabled() -> bool:
    """MXTPU_TELEMETRY gate (default on); 0/false/off makes every hook
    in this module a no-op."""
    return os.environ.get("MXTPU_TELEMETRY", "1").lower() \
        not in ("0", "false", "off", "")


def telemetry_path():
    """MXTPU_TELEMETRY_PATH: JSONL sink for step records and events;
    unset = in-memory ring only (`recent_steps()`)."""
    return os.environ.get("MXTPU_TELEMETRY_PATH") or None


# -- metrics registry ----------------------------------------------------------

class Counter:
    """Monotonic counter (steps, bytes, accumulated wait time)."""

    __slots__ = ("name", "value")

    def __init__(self, name):
        self.name = name
        self.value = 0

    def inc(self, n=1):
        with _LOCK:
            self.value += n


class Gauge:
    """Last-write-wins instantaneous value (queue depth, loss scale)."""

    __slots__ = ("name", "value")

    def __init__(self, name):
        self.name = name
        self.value = None

    def set(self, v):
        self.value = v


class Histogram:
    """Time/byte distribution: count, total, min, max (the same shape
    as the profiler's aggregate table — enough for stall attribution
    without per-sample storage)."""

    __slots__ = ("name", "count", "total", "min", "max")

    def __init__(self, name):
        self.name = name
        self.count = 0
        self.total = 0.0
        self.min = float("inf")
        self.max = 0.0

    def observe(self, v):
        with _LOCK:
            self.count += 1
            self.total += v
            if v < self.min:
                self.min = v
            if v > self.max:
                self.max = v

    def summary(self):
        return {"count": self.count, "total": self.total,
                "min": self.min if self.count else None, "max": self.max}


class MetricsRegistry:
    """Process-wide named-metric store.  `counter`/`gauge`/`histogram`
    create-or-return; `snapshot()` is the read surface the per-step
    assembler and tests use."""

    def __init__(self):
        self._metrics = {}

    def _get(self, name, cls):
        m = self._metrics.get(name)
        if m is None:
            with _LOCK:
                m = self._metrics.get(name)
                if m is None:
                    m = cls(name)
                    self._metrics[name] = m
        if not isinstance(m, cls):
            raise TypeError(f"metric {name!r} already registered as "
                            f"{type(m).__name__}")
        return m

    def counter(self, name) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name) -> Histogram:
        return self._get(name, Histogram)

    def snapshot(self) -> dict:
        out = {}
        for name, m in list(self._metrics.items()):
            if isinstance(m, Histogram):
                out[name] = m.summary()
            else:
                out[name] = m.value
        return out

    def reset(self):
        with _LOCK:
            self._metrics.clear()


REGISTRY = MetricsRegistry()


def count(name, n=1):
    """Shorthand hook for hot paths: no-op when telemetry is off."""
    if enabled():
        REGISTRY.counter(name).inc(n)


def gauge_set(name, v):
    if enabled():
        REGISTRY.gauge(name).set(v)


def observe(name, v):
    if enabled():
        REGISTRY.histogram(name).observe(v)


# -- run identity and the JSONL sink -------------------------------------------

_RUN_ID = f"{os.getpid():x}-{int(time.time() * 1000) & 0xffffffff:08x}"
_SINK = None          # (path, file object)
_SINK_SIZE = 0        # bytes written to the current sink file
_RECENT = []          # bounded ring of step records (bench.py reads it)
_RECENT_MAX = 256
_EVENT_COUNTS = {}    # event kind -> count (cheap test/report surface)


def run_id() -> str:
    return _RUN_ID


# -- fleet identity (schema v3) ------------------------------------------------
#
# Every record is stamped with the emitting process's place in the
# fleet so the obs collector can aggregate per-rank logs into one
# FleetView.  Identity resolves lazily from MXTPU_WORKER_RANK /
# MXTPU_NUM_WORKERS and is overridden explicitly by ElasticGang /
# ReplicaServer via set_identity() (reshapes update world in place).
# The dict is cached: stamping costs two dict lookups per record,
# invisible against the <1% overhead budget.

_IDENT = None


def _identity() -> dict:
    global _IDENT
    if _IDENT is None:
        ident = {}
        try:
            r = os.environ.get("MXTPU_WORKER_RANK")
            w = os.environ.get("MXTPU_NUM_WORKERS")
            if r is not None:
                ident["rank"] = int(r)
            if w is not None:
                ident["world"] = int(w)
        except ValueError:
            ident = {}
        _IDENT = ident
    return _IDENT


def set_identity(rank=None, world=None, replica_id=None):
    """Declare this process's fleet identity; subsequent records carry
    the fields.  Partial updates merge (a reshape only changes world)."""
    global _IDENT
    ident = dict(_identity())
    if rank is not None:
        ident["rank"] = int(rank)
    if world is not None:
        ident["world"] = int(world)
    if replica_id is not None:
        ident["replica_id"] = int(replica_id)
    _IDENT = ident


def identity() -> dict:
    """The current identity stamp (possibly empty) — read surface for
    obs/collector.py and tests."""
    return dict(_identity())


def _sink_file():
    """Lazily opened append-only JSONL file; reopened if the configured
    path changes (tests point it at per-test tmp dirs)."""
    global _SINK, _SINK_SIZE
    path = telemetry_path()
    with _LOCK:
        if path is None:
            if _SINK is not None:
                try:
                    _SINK[1].close()
                except OSError:
                    pass
                _SINK = None
            return None
        if _SINK is None or _SINK[0] != path:
            if _SINK is not None:
                try:
                    _SINK[1].close()
                except OSError:
                    pass
            f = open(path, "a", encoding="utf-8")
            _SINK = (path, f)
            try:
                _SINK_SIZE = os.path.getsize(path)
            except OSError:
                _SINK_SIZE = 0
        return _SINK[1]


def _max_sink_bytes():
    """MXTPU_TELEMETRY_MAX_MB → byte cap on the JSONL sink, or None
    (unbounded, the default)."""
    raw = os.environ.get("MXTPU_TELEMETRY_MAX_MB")
    if not raw:
        return None
    try:
        mb = float(raw)
    except ValueError:
        return None
    return int(mb * 1e6) if mb > 0 else None


def _rotate_locked(res):
    """Rotate the sink: close, rename to ``<path>.1`` (atomic on the
    same filesystem), reopen fresh.  Caller holds _LOCK.  The
    ``telemetry_rotate`` fault site crashes BETWEEN the rename and the
    reopen — the torn-rotation window readers must survive (``.1``
    complete, the live path momentarily absent)."""
    global _SINK, _SINK_SIZE
    path, f = _SINK
    try:
        f.close()
    except OSError:
        pass
    try:
        os.replace(path, path + ".1")
    except OSError:
        pass               # rename failure: keep appending in place
    if res is not None and res.consume_fault("telemetry_rotate"):
        os._exit(res.CRASH_EXIT_CODE)
    nf = open(path, "a", encoding="utf-8")
    _SINK = (path, nf)
    _SINK_SIZE = 0
    return nf


def _emit(record):
    """Append one record to the ring and (when configured) the JSONL
    log.  One line per record, flushed immediately: a crash between
    records loses nothing, a crash mid-write truncates only the last
    line (readers skip it).  When MXTPU_TELEMETRY_MAX_MB is set the
    sink rotates to ``<path>.1`` before the write that would cross the
    cap."""
    global _SINK_SIZE
    for k, v in _identity().items():
        record.setdefault(k, v)
    with _LOCK:
        _RECENT.append(record)
        del _RECENT[:-_RECENT_MAX]
    f = _sink_file()
    if f is None:
        return
    line = json.dumps(record, separators=(",", ":")) + "\n"
    try:
        from . import resilience as _res
    except ImportError:        # standalone import (tools/trace_report)
        _res = None
    with _LOCK:
        cap = _max_sink_bytes()
        if cap is not None and _SINK_SIZE > 0 \
                and _SINK_SIZE + len(line) > cap:
            f = _rotate_locked(_res)
        if _res is not None and _res.consume_fault("telemetry_crash"):
            # hermetic crash-mid-append: half a line, then power loss
            f.write(line[:max(1, len(line) // 2)])
            f.flush()
            os._exit(_res.CRASH_EXIT_CODE)
        try:
            f.write(line)
            f.flush()
            _SINK_SIZE += len(line)
        except OSError:
            pass               # telemetry must never kill training


# -- incremental JSONL tailing (obs/collector.py polls these) ------------------
#
# The collector re-reads the per-rank logs every MXTPU_OBS_ROLLUP_SECS;
# a full re-parse would be O(log size) per poll.  Each tailed path
# keeps a seek offset so a poll costs O(new bytes) — pinned by
# tests/test_obs.py via tail_bytes_read().  Rotation (the sink moving
# to ``<path>.1`` under the reader) is detected by inode change or
# shrink; the remainder of the rotated file is drained from the old
# offset before the fresh file is read from 0, so no record is lost
# across the boundary — including the torn-rotation window where the
# live path briefly does not exist.

_TAILS = {}           # path -> {"off", "ino", "r1_off"}
_TAIL_RINGS = {}      # path -> bounded list of parsed records
_TAIL_BYTES = 0       # total bytes read by _read_lines (test pin)
_TAIL_STRIKES = {}    # path -> [tail_start, tail_len, polls_held]


def _tail_strikes_max(default=3) -> int:
    """MXTPU_TELEMETRY_TAIL_STRIKES: polls the SAME half-flushed tail
    may be held back before it is skipped as torn (default 3)."""
    try:
        v = int(os.environ.get("MXTPU_TELEMETRY_TAIL_STRIKES", default))
    except ValueError:
        v = default
    return max(2, v)


def _tail_strike(path, tail_start, tail_len, new_off):
    """Torn-tail strike accounting.  A half-flushed line is normally
    held back (re-read next poll) until its newline lands — but a line
    that NEVER completes (writer died mid-append, bit-rot ate the
    newline) would otherwise wedge the tail forever, silently.  After
    the identical byte range is held back ``_tail_strikes_max()``
    polls in a row, skip past it and emit one ``telemetry_torn_line``
    event so the corruption is visible.  A growing tail (len changes)
    resets the count — only a genuinely stuck line strikes out."""
    st = _TAIL_STRIKES.get(path)
    if st is not None and st[0] == tail_start and st[1] == tail_len:
        st[2] += 1
    else:
        st = _TAIL_STRIKES[path] = [tail_start, tail_len, 1]
    if st[2] < _tail_strikes_max():
        return new_off
    del _TAIL_STRIKES[path]
    event("telemetry_torn_line", path=os.path.basename(path),
          offset=int(tail_start), bytes=int(tail_len))
    return tail_start + tail_len


def tail_bytes_read() -> int:
    return _TAIL_BYTES


def _read_lines(path, start):
    """Parse complete JSONL lines from `path` starting at byte
    `start`; returns (records, new_offset).  The offset only advances
    past the last newline, so a half-flushed tail is re-read (not
    skipped) on the next poll."""
    global _TAIL_BYTES
    try:
        with open(path, "rb") as f:
            f.seek(start)
            data = f.read()
    except OSError:
        return [], start
    if not data:
        return [], start
    _TAIL_BYTES += len(data)
    nl = data.rfind(b"\n")
    if nl < 0:
        return [], _tail_strike(path, start, len(data), start)
    recs = []
    for raw in data[:nl + 1].splitlines():
        try:
            recs.append(json.loads(raw))
        except ValueError:
            pass               # torn line mid-file (crash artifact)
    new_off = start + nl + 1
    tail = len(data) - (nl + 1)
    if tail:
        new_off = _tail_strike(path, new_off, tail, new_off)
    else:
        _TAIL_STRIKES.pop(path, None)
    return recs, new_off


def tail_records(path):
    """Newly appended records of `path` since the previous call
    (per-path seek offset; O(new bytes)), reading across a sink
    rotation without loss."""
    st = _TAILS.get(path)
    if st is None:
        # bootstrap: an already-rotated predecessor (including the
        # torn-rotation case where the live path does not exist yet)
        # is drained before the live file, oldest records first
        st = _TAILS[path] = {
            "off": 0, "ino": None,
            "r1_off": 0 if os.path.exists(path + ".1") else None}
    try:
        s = os.stat(path)
        size, ino = s.st_size, s.st_ino
    except OSError:
        size = ino = None
    rotated = (
        (size is None and st["off"] > 0) or
        (size is not None and size < st["off"]) or
        (ino is not None and st["ino"] is not None and ino != st["ino"]))
    out = []
    if rotated:
        # what we were reading is now <path>.1: drain its remainder
        if st["r1_off"] is None:
            st["r1_off"] = st["off"]
        st["off"] = 0
        st["ino"] = None
    if st["r1_off"] is not None:
        recs, new_off = _read_lines(path + ".1", st["r1_off"])
        out.extend(recs)
        # keep tracking .1 only while the live file is absent (torn
        # rotation); once it exists the rotated file is frozen
        st["r1_off"] = new_off if size is None else None
    if size is not None:
        recs, st["off"] = _read_lines(path, st["off"])
        st["ino"] = ino
        out.extend(recs)
    return out


def _tail_ring(path):
    ring = _TAIL_RINGS.get(path)
    if ring is None:
        ring = _TAIL_RINGS[path] = []
    new = tail_records(path)
    if new:
        ring.extend(new)
        del ring[:-_RECENT_MAX]
    return ring


def recent_steps(path=None, include_trials=False, jsonl=None):
    """Step records, oldest first (optionally filtered by step path:
    'captured' / 'eager' / 'manual').  Default source is the in-memory
    ring; pass ``jsonl=`` to incrementally tail a JSONL log instead
    (O(new lines) per call — the collector's read path).  Autotune
    trial steps are EXCLUDED by default: they time candidate configs,
    not the run's steady state (pass include_trials=True to see them)."""
    if jsonl is not None:
        recs = [r for r in _tail_ring(jsonl) if r.get("type") == "step"]
    else:
        with _LOCK:
            recs = [r for r in _RECENT if r.get("type") == "step"]
    if not include_trials:
        recs = [r for r in recs if not r.get("tuning_trial")]
    if path is not None:
        recs = [r for r in recs if r.get("path") == path]
    return recs


def event_counts() -> dict:
    with _LOCK:
        return dict(_EVENT_COUNTS)


def reset(close_sink=True):
    """Drop ring, event counts, inter-step state, and (optionally) the
    sink handle — test isolation, not a runtime API."""
    global _SINK, _SINK_SIZE, _LAST_END, _LAST_COUNTS, _CURRENT
    global _PEAK_CACHE, _TRIAL_FP, _CONFIG_FP, _IDENT, _TAIL_BYTES
    global _GANG_EPOCH
    with _LOCK:
        _RECENT.clear()
        _EVENT_COUNTS.clear()
    _CURRENT = None
    _TRIAL_FP = None
    _CONFIG_FP = None
    _GANG_EPOCH = None
    _LAST_END = None
    _LAST_COUNTS = {}
    _PEAK_CACHE = None
    _IDENT = None
    _TAILS.clear()
    _TAIL_RINGS.clear()
    _TAIL_STRIKES.clear()
    _TAIL_BYTES = 0
    _SINK_SIZE = 0
    if close_sink and _SINK is not None:
        try:
            _SINK[1].close()
        except OSError:
            pass
        _SINK = None


def event(kind, /, **fields):
    """Emit one discrete, run-id-stamped event record (watchdog fired,
    step skipped, divergence rollback, restart, checkpoint commit).
    The event name is positional-only so a detail field may itself be
    named ``kind`` (e.g. sdc_detected's corruption class)."""
    if not enabled():
        return
    rec = {"type": "event", "v": SCHEMA_VERSION, "run": _RUN_ID,
           "t": time.time(), "event": str(kind)}
    for k, v in fields.items():
        if v is not None:
            rec[k] = v
    with _LOCK:
        _EVENT_COUNTS[kind] = _EVENT_COUNTS.get(kind, 0) + 1
    _emit(rec)


def request_record(queue_us, prefill_us, decode_us_per_token, bucket,
                   padded_fraction, new_tokens=None, generation=None,
                   **fields):
    """Emit one per-request serving record (the serving analogue of a
    StepStats row): queue wait, prefill latency, per-token decode
    latency, the (batch, seq) bucket the request was padded into, and
    the padding overhead it paid.  tools/trace_report.py aggregates
    these into the per-request p50/p99 section."""
    if not enabled():
        return
    rec = {"type": "request", "v": SCHEMA_VERSION, "run": _RUN_ID,
           "t": time.time(),
           "queue_us": round(float(queue_us), 1),
           "prefill_us": round(float(prefill_us), 1),
           "decode_us_per_token": round(float(decode_us_per_token), 1),
           "bucket": [int(b) for b in bucket],
           "padded_fraction": float(padded_fraction)}
    if new_tokens is not None:
        rec["new_tokens"] = int(new_tokens)
    if generation is not None:
        rec["generation"] = int(generation)
    for k, v in fields.items():
        if v is not None:
            rec[k] = v
    _emit(rec)


def integrity_record(step, fp, ok, epoch=None, peers=None, corrupt=None,
                     kind=None, rank=None, **fields):
    """Emit one integrity-attestation record (schema v4): the
    fingerprint this rank published for ``step``, whether the
    cross-replica vote agreed (``ok``), how many peers voted, which
    ranks the majority named corrupt, and — after a replay audit — the
    corruption ``kind`` ("memory" | "compute" | "drift").
    tools/trace_report.py and the obs collector aggregate these into
    the integrity section."""
    if not enabled():
        return
    rec = {"type": "integrity", "v": SCHEMA_VERSION, "run": _RUN_ID,
           "t": time.time(), "step": int(step), "fp": str(fp),
           "ok": bool(ok)}
    if epoch is not None:
        rec["epoch"] = int(epoch)
    if peers is not None:
        rec["peers"] = int(peers)
    if corrupt:
        rec["corrupt"] = [int(r) for r in corrupt]
    if kind is not None:
        rec["kind"] = str(kind)
    if rank is not None:
        rec["rank"] = int(rank)
    for k, v in fields.items():
        if v is not None:
            rec[k] = v
    _emit(rec)


def recent_requests(jsonl=None):
    """Per-request serving records, oldest first: the in-memory ring,
    or (with ``jsonl=``) an incrementally tailed JSONL log."""
    if jsonl is not None:
        return [r for r in _tail_ring(jsonl) if r.get("type") == "request"]
    with _LOCK:
        return [r for r in _RECENT if r.get("type") == "request"]


# -- per-step assembly ---------------------------------------------------------

#: counters whose per-step DELTA lands in each StepStats record
_DELTA_COUNTERS = ("collective.bytes", "collective.buckets",
                   "input.wait_us", "ckpt.stall_us")

_CURRENT = None       # open _StepAccum, at most one per process
_LAST_END = None      # perf_counter at the previous step_end
_LAST_COUNTS = {}     # counter snapshot at the previous step_end


class _StepAccum:
    """Accumulator for one in-flight step (returned by `step_begin`)."""

    __slots__ = ("t0", "tid", "path", "scopes", "fields")

    def __init__(self, path):
        self.t0 = time.perf_counter()
        self.tid = threading.get_ident()
        self.path = path
        self.scopes = {}
        self.fields = {}


def step_begin(path="eager"):
    """Open the per-step accumulator; returns None when telemetry is off
    or a step is already open (nested Trainer.step inside train_step)."""
    global _CURRENT
    if not enabled() or _CURRENT is not None:
        return None
    _CURRENT = _StepAccum(path)
    return _CURRENT


def on_scope(name, dur_s):
    """Profiler scope hook: `profiler.scope.__exit__` forwards every
    annotate duration here.  Only scopes on the step-owning thread count
    toward the breakdown (producer-thread work overlaps compute)."""
    acc = _CURRENT
    if acc is None or threading.get_ident() != acc.tid:
        return
    acc.scopes[name] = acc.scopes.get(name, 0.0) + dur_s


def step_abort(acc):
    """Discard an open accumulator without emitting (step raised): the
    next step_begin must not find a stale open record."""
    global _CURRENT
    if acc is not None and acc is _CURRENT:
        _CURRENT = None


def note(**fields):
    """Attach fields (grad_norm, loss_scale, flops, cache_hit, ...) to
    the currently open step record; no-op when none is open."""
    acc = _CURRENT
    if acc is None:
        return
    for k, v in fields.items():
        if v is not None:
            acc.fields[k] = v


def note_path(path):
    acc = _CURRENT
    if acc is not None:
        acc.path = path


def step_end(acc, step=None, skipped=False):
    """Close the accumulator into one StepStats record and emit it.

    The breakdown interval is ``now - previous step_end`` (first step:
    ``now - step_begin``) so the wait for the NEXT batch — which happens
    between `train_step` calls — is attributed to the step it stalled.
    Shares, including ``other``, sum to 1.0 over that interval.
    """
    global _CURRENT, _LAST_END, _LAST_COUNTS
    if acc is None or acc is not _CURRENT:
        return None
    _CURRENT = None
    now = time.perf_counter()
    wall_us = (now - acc.t0) * 1e6
    start = _LAST_END if _LAST_END is not None else acc.t0
    interval_us = max((now - start) * 1e6, wall_us, 1e-3)
    # lock-free metric reads (dict.get is atomic; a missing metric just
    # means no traffic yet) — this runs once per training step
    metrics = REGISTRY._metrics
    counts = {}
    deltas = {}
    for name in _DELTA_COUNTERS:
        m = metrics.get(name)
        counts[name] = v = m.value if m is not None else 0
        deltas[name] = v - _LAST_COUNTS.get(name, 0)
    _LAST_END = now
    _LAST_COUNTS = counts

    parts = dict.fromkeys(_BREAKDOWN_KEYS[:-1], 0.0)
    for scope_name, dur in acc.scopes.items():
        bucket = _SCOPE_BUCKET.get(scope_name)
        if bucket is not None:
            parts[bucket] += dur * 1e6
    parts["data"] += deltas["input.wait_us"]
    known = sum(parts.values())
    parts["other"] = max(interval_us - known, 0.0)
    total = sum(parts.values()) or 1.0

    rec = {
        "type": "step", "v": SCHEMA_VERSION, "run": _RUN_ID,
        "t": time.time(),
        "step": int(step) if step is not None else None,
        "path": acc.path,
        "skipped": bool(skipped),
        # deliberately un-rounded: 16 round() calls cost ~6us/step,
        # a third of the whole mechanism's overhead budget
        "wall_us": wall_us,
        "interval_us": interval_us,
        "breakdown_us": parts,
        "shares": {k: v / total for k, v in parts.items()},
        "collective_bytes": int(deltas["collective.bytes"]),
        "collective_buckets": int(deltas["collective.buckets"]),
        "ckpt_stall_us": deltas["ckpt.stall_us"],
        "input_queue_depth": getattr(
            metrics.get("input.queue_depth"), "value", None),
    }
    flops = acc.fields.pop("flops", None)
    rec["flops"] = flops
    mfu = None
    if flops:
        peak = peak_flops()
        if peak:
            mfu = flops / (interval_us * 1e-6) / peak
    rec["mfu"] = round(mfu, 6) if mfu is not None else None
    if _TRIAL_FP is not None:
        rec["tuning_trial"] = True
        rec["config_fingerprint"] = _TRIAL_FP
    elif _CONFIG_FP is not None:
        rec["config_fingerprint"] = _CONFIG_FP
    if _GANG_EPOCH is not None:
        rec["gang_epoch"] = _GANG_EPOCH
    for k, v in acc.fields.items():
        rec[k] = v
    _emit(rec)
    return rec


# -- MFU accounting ------------------------------------------------------------

_PEAK_CACHE = None


def peak_flops():
    """Peak FLOP/s of the step's device: MXTPU_PEAK_FLOPS override,
    else the device-kind table (bf16 figures; nominal for CPU).  None
    when the kind is unknown — MFU is then reported as null rather than
    against a made-up denominator."""
    global _PEAK_CACHE
    # env override resolves into the cache too (cleared by reset()):
    # this sits on the per-step hot path, one environ read per step is
    # measurable against the <1% overhead budget
    if _PEAK_CACHE is not None:
        return _PEAK_CACHE or None
    raw = os.environ.get("MXTPU_PEAK_FLOPS")
    if raw:
        try:
            val = float(raw)
            if val > 0:
                _PEAK_CACHE = val
                return val
        except ValueError:
            pass
    try:
        import jax

        d = jax.devices()[0]
        kind = (getattr(d, "device_kind", "") or d.platform or "").lower()
    except Exception:
        return None
    val = 0.0
    for key, v in PEAK_FLOPS:
        if key in kind:
            val = v
            break
    _PEAK_CACHE = val
    return val or None


def flops_of_compiled(compiled):
    """XLA cost analysis of a `jax.stages.Compiled` → total FLOPs, or
    None when the backend does not report them."""
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else None
        if not ca:
            return None
        flops = ca.get("flops")
        return float(flops) if flops and flops > 0 else None
    except Exception:
        return None


_COLLECTIVE_RE = None


def collective_bytes_by_axis(compiled, mesh):
    """Per-device bytes moved by the step program's collectives,
    attributed to mesh axes: ``{"dp": ..., "tp": ..., "all": ...}``.

    Parses the compiled HLO text for `all-reduce` / `all-gather` /
    `reduce-scatter` / `all-to-all` / `collective-permute` ops, reads
    each op's replica groups, and attributes the op to the mesh axis
    whose size matches the group size (group stride breaking ties:
    contiguous groups are inner axes, strided groups outer; tp is
    innermost by `make_mesh`'s canonical order).  Bytes use the ring
    cost model per participating device: ``2(S-1)/S·bytes`` for
    all-reduce, ``(S-1)/S·bytes`` for all-gather / reduce-scatter /
    all-to-all, ``1·bytes`` for collective-permute.  Returns {} when
    the HLO is unavailable or parses to nothing — callers treat that
    as "no data", never as "zero collectives".
    """
    global _COLLECTIVE_RE
    import re as _re

    if _COLLECTIVE_RE is None:
        _COLLECTIVE_RE = _re.compile(
            r"=\s*(?P<shape>.+?)\s+"
            r"(?P<op>all-reduce|all-gather|reduce-scatter|all-to-all|"
            r"collective-permute)(?:-start)?\(")
    try:
        hlo = compiled.as_text()
    except Exception:
        return {}
    if not hlo:
        return {}

    dtype_bytes = {
        "pred": 1, "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e5m2": 1,
        "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
        "s32": 4, "u32": 4, "f32": 4,
        "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16,
    }
    shape_re = _re.compile(r"(\w+)\[([\d,]*)\]")

    def bytes_of(shape_txt):
        total = 0
        for dt, dims in shape_re.findall(shape_txt):
            nb = dtype_bytes.get(dt)
            if nb is None:
                continue
            n = 1
            for d in dims.split(","):
                if d:
                    n *= int(d)
            total += n * nb
        return total

    # axis sizes and strides in the mesh's device array: innermost axis
    # has stride 1, so a CONTIGUOUS replica group ({0,1},{2,3},...) of
    # size S belongs to the innermost axis of that size
    names = list(mesh.axis_names)
    sizes = [mesh.shape[n] for n in names]
    strides = {}
    acc = 1
    for n, s in zip(reversed(names), reversed(sizes)):
        strides[n] = acc
        acc *= s

    def axis_of(group_size, contiguous):
        if group_size >= mesh.size:
            return "all"
        cands = [n for n in names if mesh.shape[n] == group_size]
        if not cands:
            return "other"
        if len(cands) == 1:
            return cands[0]
        # tie: contiguous groups ⇒ smallest stride (innermost axis)
        key = (lambda n: strides[n]) if contiguous \
            else (lambda n: -strides[n])
        return sorted(cands, key=key)[0]

    out = {}
    for line in hlo.splitlines():
        m = _COLLECTIVE_RE.search(line)
        if m is None or "-done" in line[:m.start()]:
            continue
        op = m.group("op")
        shape_txt = m.group("shape")
        group_size, contiguous = mesh.size, True
        gm = _re.search(r"replica_groups=\{(\{[\d,]+\})", line)
        if gm is not None:
            first = [int(x) for x in
                     gm.group(1).strip("{}").split(",") if x]
            group_size = max(len(first), 1)
            contiguous = all(b - a == 1
                             for a, b in zip(first, first[1:]))
        else:
            gm = _re.search(
                r"replica_groups=\[(\d+),(\d+)\]<=\[[\d,]+\](T\()?",
                line)
            if gm is not None:
                group_size = max(int(gm.group(2)), 1)
                contiguous = gm.group(3) is None
        s = group_size
        nbytes = bytes_of(shape_txt)
        if op == "all-reduce":
            moved = 2.0 * (s - 1) / s * nbytes
        elif op == "collective-permute":
            moved = float(nbytes)
        else:
            # all-gather bytes from the RESULT shape, reduce-scatter
            # from the operand — the printed shape is the result either
            # way; for reduce-scatter the operand is S× the result, so
            # (S-1)/S·operand == (S-1)·result
            if op == "reduce-scatter":
                moved = float(s - 1) * nbytes
            else:
                moved = (s - 1) / s * nbytes
        axis = axis_of(s, contiguous)
        out[axis] = out.get(axis, 0) + int(moved)
    return out


# -- schema validation (tests + tools/trace_report.py --validate) --------------

def _validate_spans(spans, fail):
    """A request's ``spans`` field must be one CLOSED causal tree:
    every span has an id/name/t0/dur_us, exactly one root (parent
    null), and every parent id resolves inside the list."""
    if not isinstance(spans, list) or not spans:
        fail("spans must be a non-empty list")
    ids = set()
    roots = 0
    for sp in spans:
        if not isinstance(sp, dict):
            fail("each span must be an object")
        sid = sp.get("span_id")
        if not isinstance(sid, str) or not sid:
            fail("span_id must be a non-empty string")
        if sid in ids:
            fail(f"duplicate span_id {sid!r}")
        ids.add(sid)
        if not isinstance(sp.get("name"), str) or not sp["name"]:
            fail("span name must be a non-empty string")
        if not isinstance(sp.get("t0"), (int, float)):
            fail("span t0 must be a number (epoch seconds)")
        dur = sp.get("dur_us")
        if not isinstance(dur, (int, float)) or dur < 0:
            fail("span dur_us must be a non-negative number "
                 "(open spans may not be emitted)")
        if sp.get("parent") is None:
            roots += 1
    if roots != 1:
        fail(f"spans must have exactly one root, got {roots}")
    for sp in spans:
        parent = sp.get("parent")
        if parent is not None and parent not in ids:
            fail(f"span parent {parent!r} not in tree")


def validate_record(rec):
    """Raise ValueError unless `rec` is a well-formed telemetry record.
    The authoritative schema spec lives in docs/observability.md."""

    def fail(msg):
        raise ValueError(f"telemetry record invalid: {msg}; record={rec!r}")

    if not isinstance(rec, dict):
        fail("not an object")
    kind = rec.get("type")
    if kind not in ("step", "event", "request", "integrity"):
        fail(f"type must be 'step'|'event'|'request'|'integrity', "
             f"got {kind!r}")
    if not isinstance(rec.get("run"), str) or not rec["run"]:
        fail("missing run id")
    if not isinstance(rec.get("t"), (int, float)):
        fail("missing timestamp t")
    if rec.get("v") not in _ACCEPTED_VERSIONS:
        fail(f"schema version {rec.get('v')!r} not in "
             f"{_ACCEPTED_VERSIONS}")
    # optional fleet-identity fields (schema v3): any record type
    for key, lo in (("rank", 0), ("world", 1), ("replica_id", 0)):
        val = rec.get(key)
        if val is not None and (not isinstance(val, int) or
                                isinstance(val, bool) or val < lo):
            fail(f"{key} must be an int >= {lo} or absent")
    if kind == "request":
        tid = rec.get("trace_id")
        if tid is not None and (not isinstance(tid, str) or not tid):
            fail("trace_id must be a non-empty string or absent")
        spans = rec.get("spans")
        if spans is not None:
            _validate_spans(spans, fail)
        for key in ("queue_us", "prefill_us", "decode_us_per_token"):
            val = rec.get(key)
            if not isinstance(val, (int, float)) or val < 0:
                fail(f"{key} must be a non-negative number")
        bucket = rec.get("bucket")
        if not (isinstance(bucket, list) and len(bucket) == 2 and
                all(isinstance(b, int) and b > 0 for b in bucket)):
            fail("bucket must be [batch, seq] positive ints")
        pf = rec.get("padded_fraction")
        if not isinstance(pf, (int, float)) or not 0 <= pf < 1:
            fail("padded_fraction must be a number in [0, 1)")
        for key in ("new_tokens", "generation"):
            val = rec.get(key)
            if val is not None and \
                    (not isinstance(val, int) or val < 0):
                fail(f"{key} must be a non-negative int or absent")
        de = rec.get("deadline_exceeded")
        if de is not None and not isinstance(de, bool):
            fail("deadline_exceeded must be a bool or absent")
        return rec
    if kind == "event":
        if not isinstance(rec.get("event"), str) or not rec["event"]:
            fail("event record missing event kind")
        step = rec.get("step")
        if step is not None and not isinstance(step, int):
            fail("event step must be an int")
        return rec
    if kind == "integrity":
        # schema v4: one attestation round
        step = rec.get("step")
        if not isinstance(step, int) or isinstance(step, bool) or \
                step < 0:
            fail("integrity step must be a non-negative int")
        fp = rec.get("fp")
        if not isinstance(fp, str) or not fp:
            fail("integrity fp must be a non-empty string")
        if not isinstance(rec.get("ok"), bool):
            fail("integrity ok must be a bool")
        for key in ("epoch", "peers"):
            val = rec.get(key)
            if val is not None and (not isinstance(val, int) or
                                    isinstance(val, bool) or val < 0):
                fail(f"integrity {key} must be a non-negative int "
                     f"or absent")
        corrupt = rec.get("corrupt")
        if corrupt is not None and not (
                isinstance(corrupt, list) and
                all(isinstance(r, int) and not isinstance(r, bool)
                    and r >= 0 for r in corrupt)):
            fail("integrity corrupt must be a list of ranks or absent")
        ik = rec.get("kind")
        if ik is not None and ik not in ("memory", "compute", "drift"):
            fail(f"integrity kind must be memory|compute|drift, "
                 f"got {ik!r}")
        return rec
    if rec.get("step") is not None and not isinstance(rec["step"], int):
        fail("step must be an int or null")
    if rec.get("path") not in ("captured", "eager", "manual"):
        fail(f"unknown path {rec.get('path')!r}")
    if not isinstance(rec.get("skipped"), bool):
        fail("skipped must be a bool")
    for key in ("wall_us", "interval_us"):
        val = rec.get(key)
        if not isinstance(val, (int, float)) or val < 0:
            fail(f"{key} must be a non-negative number")
    for section in ("breakdown_us", "shares"):
        obj = rec.get(section)
        if not isinstance(obj, dict) or \
                set(obj) != set(_BREAKDOWN_KEYS):
            fail(f"{section} must have keys {_BREAKDOWN_KEYS}")
        for k, val in obj.items():
            if not isinstance(val, (int, float)) or val < 0:
                fail(f"{section}[{k}] must be a non-negative number")
    total = sum(rec["shares"].values())
    if not 0.98 <= total <= 1.02:
        fail(f"shares sum to {total}, expected ~1.0")
    for key in ("collective_bytes", "collective_buckets"):
        if not isinstance(rec.get(key), int) or rec[key] < 0:
            fail(f"{key} must be a non-negative int")
    for key in ("flops", "mfu", "grad_norm", "loss_scale"):
        val = rec.get(key)
        if val is not None and not isinstance(val, (int, float)):
            fail(f"{key} must be a number or null")
    if rec.get("cache_hit") is not None and \
            not isinstance(rec["cache_hit"], bool):
        fail("cache_hit must be a bool or null")
    # optional autotune fields (schema v2): absent on untuned runs
    tt = rec.get("tuning_trial")
    if tt is not None and not isinstance(tt, bool):
        fail("tuning_trial must be a bool or absent")
    cfp = rec.get("config_fingerprint")
    if cfp is not None and \
            (not isinstance(cfp, str) or not cfp):
        fail("config_fingerprint must be a non-empty string or absent")
    # optional sharded-step fields (PR 9): absent on unsharded runs
    cba = rec.get("collective_bytes_by_axis")
    if cba is not None:
        if not isinstance(cba, dict):
            fail("collective_bytes_by_axis must be an object or absent")
        for k, val in cba.items():
            if not isinstance(k, str) or \
                    not isinstance(val, int) or val < 0:
                fail("collective_bytes_by_axis entries must be "
                     "str → non-negative int")
    peak = rec.get("device_peak_bytes")
    if peak is not None and \
            (not isinstance(peak, (int, float)) or peak < 0):
        fail("device_peak_bytes must be a non-negative number or absent")
    # optional pipeline field (schema v5): absent off the pp schedule
    bf = rec.get("bubble_fraction")
    if bf is not None and \
            (not isinstance(bf, (int, float)) or not 0 <= bf < 1):
        fail("bubble_fraction must be a number in [0, 1) or absent")
    # optional sparse-embedding fields (schema v6): absent on dense steps
    lu = rec.get("lookup_us")
    if lu is not None and \
            (not isinstance(lu, (int, float)) or lu < 0):
        fail("lookup_us must be a non-negative number or absent")
    uf = rec.get("unique_fraction")
    if uf is not None and \
            (not isinstance(uf, (int, float)) or not 0 < uf <= 1):
        fail("unique_fraction must be a number in (0, 1] or absent")
    # optional input-pipeline field (schema v7): absent when no
    # resumable pipeline is attached to the trainer
    ss = rec.get("samples_seen")
    if ss is not None and \
            (not isinstance(ss, int) or isinstance(ss, bool) or ss < 0):
        fail("samples_seen must be a non-negative int or absent")
    # optional gang-fencing field (schema v8): absent outside an
    # elastic gang
    ge = rec.get("gang_epoch")
    if ge is not None and \
            (not isinstance(ge, int) or isinstance(ge, bool) or ge < 0):
        fail("gang_epoch must be a non-negative int or absent")
    return rec
