"""Memory-saving recompute (rematerialization) — named-policy registry.

Reference parity: the gradient-mirroring pass enabled by
``MXNET_BACKWARD_DO_MIRROR`` (SURVEY.md §2.5 memory-saving recompute —
nnvm Gradient pass mirror_fun).  TPU-first, this is ``jax.checkpoint``:
the backward pass recomputes activations instead of saving them, trading
FLOPs for HBM.

Knobs (any works; precedence explicit arg > MXTPU_REMAT >
MXNET_BACKWARD_DO_MIRROR):
- ``net.hybridize(remat='full'|'dots'|'dots_no_batch')``
- ``parallel.ShardedTrainer(..., remat=...)``
- env ``MXTPU_REMAT=<policy>`` → default policy wherever no explicit
  remat argument was given (the autotuner's knob: every policy here is
  numerics-preserving, so mxnet_tpu/autotune searches it by default).
- env ``MXNET_BACKWARD_DO_MIRROR=1`` → default policy 'full' (the
  reference's env semantics).

Registered policies (`names()`):
- 'none': explicit no-remat — overrides MXNET_BACKWARD_DO_MIRROR.
- 'full' (aliases 'all', True): save nothing — recompute the whole
  forward in the backward pass (maximum memory saving, one extra
  forward of FLOPs).
- 'dots': save MXU results (matmul/conv outputs), recompute the cheap
  elementwise chains — the usual sweet spot on TPU, where HBM
  bandwidth, not FLOPs, is the constraint.
- 'dots_no_batch': like 'dots' but excludes batch-dim dots.
- 'save_every_k:N': trunk-level policy over the scanned ``*_stack_*``
  transformer trunk (ops/attention.py scan_transformer_encoder) — the
  depth-L layer scan regroups into L/N chunks of N layers with one
  ``jax.checkpoint`` per chunk, so O(L/N) chunk boundaries stay
  resident instead of O(L) layers of activations.  `wrap` is a no-op
  for it (the policy lives inside the scan, not at the jit boundary);
  off-trunk models silently get no remat under it.
"""

from __future__ import annotations

import os

from .base import MXNetError

_SAVE_EVERY_PREFIX = "save_every_k:"

#: canonical policy name -> zero-arg factory returning the
#: ``jax.checkpoint(policy=...)`` argument.  Extend with
#: `register_policy`; parametric families (save_every_k:N) are handled
#: structurally, not per-N.
_REGISTRY = {}


def register_policy(name, checkpoint_policy):
    """Register a checkpoint-style remat policy: ``checkpoint_policy``
    is a zero-arg factory returning the ``jax.checkpoint(policy=...)``
    argument (None = save nothing)."""
    _REGISTRY[name] = checkpoint_policy


register_policy("full", lambda: None)
register_policy("dots", lambda: __import__("jax").checkpoint_policies
                .checkpoint_dots)
register_policy("dots_no_batch",
                lambda: __import__("jax").checkpoint_policies
                .checkpoint_dots_with_no_batch_dims)


def names():
    """All selectable policy names (the parametric save_every_k family
    is shown once, with its N placeholder)."""
    return ("none",) + tuple(_REGISTRY) + ("all", "save_every_k:N")


def parse_save_every(policy):
    """N for 'save_every_k:N', else None."""
    if isinstance(policy, str) and policy.startswith(_SAVE_EVERY_PREFIX):
        try:
            n = int(policy[len(_SAVE_EVERY_PREFIX):])
        except ValueError:
            raise MXNetError(f"bad remat policy {policy!r}: N must be "
                             "an int >= 1")
        if n < 1:
            raise MXNetError(f"bad remat policy {policy!r}: N must be "
                             ">= 1")
        return n
    return None


def canonical(remat):
    """Normalize a remat spec to a canonical policy name or None
    (no remat).  Unknown names raise MXNetError."""
    if remat is None or remat is False:
        return None
    if remat is True:
        return "full"
    name = str(remat)
    if name in ("none", ""):
        return None
    if name == "all":
        return "full"
    if name in _REGISTRY or parse_save_every(name) is not None:
        return name
    raise MXNetError(
        f"unknown remat policy {name!r}: use one of {names()}")


def env_policy():
    """The MXTPU_REMAT env policy (canonical), or None when
    unset/'none'."""
    return canonical(os.environ.get("MXTPU_REMAT") or None)


def env_default(remat):
    """Resolve the effective policy: explicit argument first (including
    an explicit 'none'), then MXTPU_REMAT, then the reference's
    MXNET_BACKWARD_DO_MIRROR → 'full'."""
    if remat is not None:
        return canonical(remat)
    raw = os.environ.get("MXTPU_REMAT")
    if raw:
        return canonical(raw)
    if os.environ.get("MXNET_BACKWARD_DO_MIRROR", "0") not in ("0", ""):
        return "full"
    return None


def wrap(fn, remat):
    """Wrap a traceable function in jax.checkpoint per the policy
    (None/'none' → unchanged).  'save_every_k:N' also returns the
    function unchanged: that policy applies inside the scanned trunk
    (`trunk_policy`), not at the jit boundary."""
    remat = env_default(remat)
    if not remat or parse_save_every(remat) is not None:
        return fn
    import jax

    factory = _REGISTRY.get(remat)
    if factory is None:
        raise MXNetError(
            f"unknown remat policy {remat!r}: use one of {names()}")
    return jax.checkpoint(fn, policy=factory())


def trunk_policy(remat):
    """Resolve the remat policy for the scanned transformer trunk.

    Returns ('layer', checkpoint_policy) for per-layer checkpointing,
    ('every', N) for chunked save_every_k, or None.  An explicit
    truthy ``remat`` argument on the op wins (True → per-layer, the
    pre-registry behaviour); otherwise only the env *save_every_k*
    policy applies here — whole-fwd policies ('full'/'dots'/...) are
    applied once at the capture/jit boundary by `wrap`, and applying
    them per-layer too would checkpoint twice."""
    if remat:
        name = canonical(remat)
        if name is None:
            return None
        n = parse_save_every(name)
        if n is not None:
            return ("every", n)
        return ("layer", _REGISTRY[name]())
    envp = env_default(None)
    n = parse_save_every(envp)
    if n is not None:
        return ("every", n)
    return None
