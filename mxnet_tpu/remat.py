"""Memory-saving recompute (rematerialization).

Reference parity: the gradient-mirroring pass enabled by
``MXNET_BACKWARD_DO_MIRROR`` (SURVEY.md §2.5 memory-saving recompute —
nnvm Gradient pass mirror_fun).  TPU-first, this is ``jax.checkpoint``:
the backward pass recomputes activations instead of saving them, trading
FLOPs for HBM.

Knobs (either works):
- ``net.hybridize(remat='full'|'dots'|'dots_no_batch')``
- ``parallel.ShardedTrainer(..., remat=...)``
- env ``MXNET_BACKWARD_DO_MIRROR=1`` → default policy 'full' wherever no
  explicit remat argument was given (the reference's env semantics).

Policies:
- 'full'  (or True): save nothing — recompute the whole forward in the
  backward pass (maximum memory saving, one extra forward of FLOPs).
- 'dots': save MXU results (matmul/conv outputs), recompute the
  cheap elementwise chains — the usual sweet spot on TPU, where HBM
  bandwidth, not FLOPs, is the constraint.
- 'dots_no_batch': like 'dots' but excludes batch-dim dots.
"""

from __future__ import annotations

import os

from .base import MXNetError


def env_default(remat):
    """Apply the MXNET_BACKWARD_DO_MIRROR env default when unset."""
    if remat is None and os.environ.get("MXNET_BACKWARD_DO_MIRROR",
                                        "0") not in ("0", ""):
        return "full"
    return remat


def wrap(fn, remat):
    """Wrap a traceable function in jax.checkpoint per the policy name
    (None → unchanged)."""
    remat = env_default(remat)
    if not remat:
        return fn
    import jax

    if remat is True or remat == "full":
        policy = None  # save nothing
    elif remat == "dots":
        policy = jax.checkpoint_policies.checkpoint_dots
    elif remat == "dots_no_batch":
        policy = jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims
    else:
        raise MXNetError(
            f"unknown remat policy {remat!r}: use 'full', 'dots', or "
            f"'dots_no_batch'")
    return jax.checkpoint(fn, policy=policy)
