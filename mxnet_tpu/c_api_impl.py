"""Python-side implementation of the flat C API.

Reference parity: src/c_api/c_api.cc + c_api_ndarray.cc — the reference's
C ABI wraps its C++ engine; ours wraps the JAX/XLA engine, so the C
library (src/c_api.cc, built as libmxtpu.so) embeds CPython and calls
the helpers below.  Every function here takes/returns only simple types
(bytes, str, int, tuples, NDArray handles) so the C side needs no jax or
numpy marshalling — handles cross the ABI as opaque PyObject*.

The contract mirrors include/mxnet/c_api.h's shape: NDArray create/copy/
shape/free, MXImperativeInvoke-style op dispatch with string-encoded
params, autograd record/backward/grad, and KVStore create/init/push/pull.
"""

from __future__ import annotations

import ast

import numpy as np

_RECORD_SCOPES = []
_KVSTORES = {}
_NEXT_KV = [1]


def create(buf, shape, dtype):
    """bytes + shape + dtype name -> NDArray handle."""
    from . import ndarray as nd

    arr = np.frombuffer(bytes(buf), dtype=np.dtype(dtype))
    arr = arr.reshape(tuple(shape)).copy()
    return nd.array(arr, dtype=np.dtype(dtype))


def to_bytes(h):
    return h.asnumpy().tobytes()


def shape_of(h):
    return tuple(int(s) for s in h.shape)


def dtype_of(h):
    return np.dtype(h.dtype).name


def size_bytes(h):
    return int(h.size) * np.dtype(h.dtype).itemsize


def invoke(name, inputs, keys, vals):
    """MXImperativeInvoke: op by registered name, params as strings
    (literal-eval'd like the reference's string-typed param dict).
    Resolves through the op registry — the same source of truth as
    MXListAllOpNames — so only real ops are invocable and unknown names
    raise cleanly.  Returns a list of output handles."""
    from .ndarray.register import invoke_registered

    kwargs = {}
    for k, v in zip(keys, vals):
        try:
            kwargs[k] = ast.literal_eval(v)
        except (ValueError, SyntaxError):
            kwargs[k] = v
    out = invoke_registered(name, tuple(inputs), kwargs)
    return list(out) if isinstance(out, (list, tuple)) else [out]


def list_op_names():
    from .ops.registry import list_ops

    return list_ops()


# -- autograd ------------------------------------------------------------------

def attach_grad(h):
    h.attach_grad()


def record_start():
    from . import autograd

    scope = autograd.record()
    scope.__enter__()
    _RECORD_SCOPES.append(scope)


def record_stop():
    if _RECORD_SCOPES:
        _RECORD_SCOPES.pop().__exit__(None, None, None)


def backward(h):
    h.backward()


def grad_of(h):
    g = h.grad
    if g is None:
        raise ValueError("no gradient attached")
    return g


# -- kvstore -------------------------------------------------------------------

def kv_create(kind):
    from . import kvstore

    kv = kvstore.create(kind)
    kid = _NEXT_KV[0]
    _NEXT_KV[0] += 1
    _KVSTORES[kid] = kv
    return kid


def kv_init(kid, key, h):
    _KVSTORES[kid].init(int(key), h)


def kv_push(kid, key, h):
    _KVSTORES[kid].push(int(key), h)


def kv_pull(kid, key):
    from . import ndarray as nd
    from .base import MXNetError

    kv = _KVSTORES[kid]
    if int(key) not in kv._store:
        raise MXNetError(f"key {int(key)} not initialized")
    out = nd.zeros(kv._store[int(key)].shape)
    kv.pull(int(key), out=out)
    return out


def kv_free(kid):
    _KVSTORES.pop(kid, None)


# -- predictor (reference: c_predict_api.h / c_predict_api.cc) -----------------

_PREDICTORS = {}
_NEXT_PRED = [1]


def pred_create(symbol_json, param_bytes, input_names):
    """symbol.json text + .params file bytes + input names -> handle.
    The deploy-format predictor: builds a SymbolBlock exactly like
    gluon.SymbolBlock.imports but from in-memory buffers (the
    reference's amalgamation/predict use case)."""
    import os
    import tempfile

    from . import symbol as sym_mod
    from .gluon.block import SymbolBlock

    sym = sym_mod.fromjson(symbol_json)
    names = [str(n) for n in input_names]
    inputs = [sym_mod.var(n) for n in names]
    block = SymbolBlock(sym, inputs)
    if param_bytes:
        fd, path = tempfile.mkstemp(suffix=".params")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(bytes(param_bytes))
            block.collect_params().load(path, cast_dtype=True,
                                        dtype_source="saved",
                                        allow_missing=False,
                                        ignore_extra=True)
        finally:
            os.remove(path)
    pid = _NEXT_PRED[0]
    _NEXT_PRED[0] += 1
    _PREDICTORS[pid] = {"block": block, "inputs": {}, "names": names,
                        "outputs": None}
    return pid


def pred_set_input(pid, key, buf, shape):
    from . import ndarray as nd

    p = _PREDICTORS[pid]
    arr = np.frombuffer(bytes(buf), dtype=np.float32).reshape(
        tuple(shape)).copy()
    p["inputs"][str(key)] = nd.array(arr)


def pred_forward(pid):
    from . import autograd

    p = _PREDICTORS[pid]
    args = [p["inputs"][n] for n in p["names"]]
    with autograd.predict_mode():
        out = p["block"](*args)
    p["outputs"] = list(out) if isinstance(out, (list, tuple)) else [out]


def pred_output_shape(pid, index):
    p = _PREDICTORS[pid]
    return tuple(int(d) for d in p["outputs"][int(index)].shape)


def pred_get_output(pid, index):
    p = _PREDICTORS[pid]
    return p["outputs"][int(index)].astype("float32").asnumpy().tobytes()


def pred_free(pid):
    _PREDICTORS.pop(int(pid), None)
