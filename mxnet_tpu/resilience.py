"""Fault-tolerance layer: retries, watchdogs, resilient training driver.

SURVEY §5.3 names checkpoint-restart as the recovery primitive for
multi-host TPU training; the failure modes this module covers are the
runtime ones that actually occur on shared TPU pools: preemption
(SIGTERM with a grace window), coordinator unreachability at rendezvous,
corrupt/truncated records on network storage, and stalled ICI/DCN
collectives that otherwise hang a process forever (the round-5 tunnel
wedge).

Four primitives, composed by the rest of the stack:

- :func:`retry_call` — exponential backoff with jitter, the single retry
  primitive behind rendezvous (``distributed.py``) and file opens
  (``recordio.py`` / ``io/io.py``).
- :class:`Watchdog` — a heartbeat thread armed around blocking device
  work (step dispatch, cross-process all-reduce, ``distributed.barrier``,
  the bench backend probe).  On expiry it dumps every Python thread's
  stack and then interrupts or aborts instead of hanging forever.
- :func:`run_resilient` — a supervised training driver composing
  ``checkpoint.PreemptionHandler`` + auto-resume-from-latest-checkpoint
  + bounded in-process restarts, with verify-after-write checkpoint
  validation and fallback to the previous checkpoint when the latest is
  corrupt or partial.
- ``MXTPU_FAULT_INJECT`` — a fault-injection env contract so every
  recovery path above is testable hermetically on CPU.

Env plane (matching storage.py's env-var style):

==============================  ================================================
``MXTPU_RENDEZVOUS_TIMEOUT``    total seconds to keep retrying rendezvous (300)
``MXTPU_RENDEZVOUS_RETRIES``    max rendezvous attempts - 1 (3)
``MXTPU_IO_RETRIES``            retries for record/file opens (2)
``MXTPU_IO_BACKOFF``            base backoff seconds for IO retries (0.05)
``MXTPU_COLLECTIVE_TIMEOUT``    watchdog seconds around eager collectives
                                (unset = no watchdog)
``MXTPU_STEP_TIMEOUT``          watchdog seconds around compiled step dispatch
                                (unset = no watchdog)
``MXTPU_WATCHDOG_ACTION``       ``interrupt`` (default) or ``abort`` — abort is
                                the only escape from a wedged C call
``MXTPU_WATCHDOG_EXIT_CODE``    process exit code for ``abort`` (124)
``MXTPU_FAULT_INJECT``          comma list of ``site[:arg]`` fault specs
==============================  ================================================

Fault-injection sites (``MXTPU_FAULT_INJECT="site:arg,site:arg"``):

- ``rendezvous:N``      — fail the next N rendezvous attempts
- ``io_open:N``         — fail the next N record/file opens
- ``corrupt_record:K``  — the K-th record a reader returns reads as corrupt
- ``sigterm_at_step:S`` — deliver SIGTERM to this process at step S
                          (honored by :func:`run_resilient`)
- ``stall_collective[:SECS]`` — stall inside the next guarded collective
                          (default 3600s — the watchdog must fire first)
- ``crash_during_save``  — hard-kill the process mid-shard-write (the
                          async checkpoint engine, checkpoint.py)
- ``crash_before_manifest`` — hard-kill after all shards are written but
                          before the manifest commit rename
- ``corrupt_shard:K``    — flip bytes in shard K of the checkpoint that
                          was just committed
"""

from __future__ import annotations

import contextlib
import os
import pickle
import random as _random
import signal
import struct
import sys
import threading
import time
import traceback
import zlib

try:
    from .base import MXNetError
except ImportError:  # loaded standalone (bench.py orchestrator never
    MXNetError = RuntimeError  # imports the package, let alone jax)


class InjectedFault(MXNetError):
    """An error raised by the MXTPU_FAULT_INJECT test harness."""


class WatchdogExpired(MXNetError):
    """Blocking work outlived its Watchdog deadline."""


class CheckpointCorrupt(MXNetError):
    """A checkpoint failed validation (bad magic/length/checksum)."""


def _tel_event(kind, **fields):
    """Structured telemetry event, guarded: this module also loads
    standalone (bench.py orchestrator keeps its driver jax-free), where
    the relative import has no package to resolve against."""
    try:
        from . import telemetry
    except ImportError:
        return
    telemetry.event(kind, **fields)


# -- fault injection -----------------------------------------------------------

class _FaultPlan:
    """Parsed MXTPU_FAULT_INJECT with live counters."""

    def __init__(self, spec):
        self.spec = spec
        self.counts = {}   # site -> remaining trigger count
        self.args = {}     # site -> numeric arg (step index, seconds, ...)
        for item in (spec or "").split(","):
            item = item.strip()
            if not item:
                continue
            site, _, arg = item.partition(":")
            if site in ("rendezvous", "io_open", "nan_grad", "inf_loss",
                        "crash_during_save", "crash_before_manifest",
                        "telemetry_crash"):
                # nan_grad: poison one gradient with NaN before health
                # assessment (consumed by the Trainer's numerics guard);
                # inf_loss: corrupt the loss seen by
                # numerics.DivergenceMonitor.observe;
                # telemetry_crash: kill the process mid-JSONL-append
                # (telemetry._emit) to prove the log stays parseable
                self.counts[site] = int(arg) if arg else 1
            elif site in ("corrupt_record", "sigterm_at_step",
                          "corrupt_shard"):
                self.args[site] = int(arg) if arg else 0
                self.counts[site] = 1
            elif site in ("stall_collective", "stall"):
                self.args["stall_collective"] = float(arg) if arg else 3600.0
                self.counts["stall_collective"] = 1
            else:
                raise MXNetError(
                    f"MXTPU_FAULT_INJECT: unknown site {site!r} in "
                    f"{spec!r}")

    def consume(self, site):
        """True (and decrements) while the site still has failures left."""
        n = self.counts.get(site, 0)
        if n <= 0:
            return False
        self.counts[site] = n - 1
        return True

    def arg(self, site):
        return self.args.get(site)


_PLAN = None
_PLAN_SPEC = None
_PLAN_LOCK = threading.Lock()


def _plan():
    """The plan for the CURRENT env value; counters persist while the env
    is unchanged, and a change (tests flipping the fixture) re-parses."""
    global _PLAN, _PLAN_SPEC
    spec = os.environ.get("MXTPU_FAULT_INJECT")
    with _PLAN_LOCK:
        if spec != _PLAN_SPEC:
            _PLAN = _FaultPlan(spec) if spec else None
            _PLAN_SPEC = spec
        return _PLAN


def reset_faults():
    """Drop cached injection counters (the `faults` conftest fixture)."""
    global _PLAN, _PLAN_SPEC
    with _PLAN_LOCK:
        _PLAN = None
        _PLAN_SPEC = None


def inject_failure(site):
    """Raise InjectedFault if the site has injected failures remaining."""
    plan = _plan()
    if plan is not None and plan.consume(site):
        raise InjectedFault(f"injected {site} failure "
                            f"(MXTPU_FAULT_INJECT={plan.spec})")


def fault_arg(site):
    """The numeric argument of an armed site, or None (does not consume)."""
    plan = _plan()
    return None if plan is None else plan.arg(site)


def consume_fault(site):
    """True once per armed count for the site (non-raising variant)."""
    plan = _plan()
    return plan is not None and plan.consume(site)


def fault_armed(site):
    """True while the site still has injected failures pending (does NOT
    consume).  Lets a fast path that cannot express a site's fault —
    e.g. the captured train step, whose gradients never materialize for
    ``nan_grad`` poisoning — route the affected step to the path that
    can."""
    plan = _plan()
    return plan is not None and plan.counts.get(site, 0) > 0


#: exit code of an injected hard crash (``crash_during_save`` /
#: ``crash_before_manifest``) — distinct from the watchdog's 124 so the
#: crash-consistency tests can assert WHICH kill fired.
CRASH_EXIT_CODE = 57


def maybe_crash(site):
    """Injected hard crash: ``os._exit`` with no cleanup, no atexit, no
    flush — the closest a test can get to power loss / OOM-kill."""
    plan = _plan()
    if plan is not None and plan.consume(site):
        sys.stderr.write(f"[resilience] injected crash at {site}\n")
        sys.stderr.flush()
        os._exit(CRASH_EXIT_CODE)


def maybe_stall(site="stall_collective"):
    """Injected stall: sleep in small interruptible increments so an
    'interrupt' watchdog can break the stall (a real wedged C collective
    needs action='abort'; see Watchdog)."""
    plan = _plan()
    if plan is None or not plan.consume("stall_collective"):
        return
    seconds = plan.arg("stall_collective") or 3600.0
    deadline = time.monotonic() + seconds
    while time.monotonic() < deadline:
        time.sleep(0.05)


# -- durable IO ----------------------------------------------------------------

def fsync_dir(path):
    """fsync a DIRECTORY so a just-renamed entry survives power loss.

    ``os.replace`` makes a write atomic but not durable: the rename
    itself lives in the directory inode, which ``fsync`` of the data
    file never touches.  Both checkpointers call this after every
    rename-commit.  Filesystems that refuse directory fds (some network
    mounts) are tolerated — they journal renames themselves.
    """
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


# -- retry primitive -----------------------------------------------------------

def retry_call(fn, *, retries=3, deadline=None, backoff=0.1,
               max_backoff=5.0, jitter=0.5, retryable=(Exception,),
               non_retryable=(), on_retry=None, description=None):
    """Call ``fn()`` with exponential-backoff-with-jitter retries.

    - ``retries``: max retry count (total attempts = retries + 1)
    - ``deadline``: total wall-clock budget in seconds; a retry whose
      backoff sleep would overshoot the deadline raises instead
    - ``retryable``/``non_retryable``: exception classes to retry / to
      re-raise immediately (non_retryable wins)
    - ``on_retry(attempt, exc, sleep_s)``: observer hook
    """
    what = description or getattr(fn, "__name__", "call")
    start = time.monotonic()
    attempt = 0
    while True:
        try:
            return fn()
        except non_retryable:
            raise
        except retryable as e:
            if attempt >= retries:
                raise
            sleep_s = min(max_backoff, backoff * (2 ** attempt))
            sleep_s *= 1.0 + jitter * _random.random()
            if deadline is not None and \
                    time.monotonic() - start + sleep_s > deadline:
                raise MXNetError(
                    f"{what}: retry deadline {deadline}s exceeded after "
                    f"{attempt + 1} attempts: {e}") from e
            if on_retry is not None:
                on_retry(attempt, e, sleep_s)
            else:
                sys.stderr.write(
                    f"[resilience] {what} failed (attempt {attempt + 1}/"
                    f"{retries + 1}): {e}; retrying in {sleep_s:.2f}s\n")
            time.sleep(sleep_s)
            attempt += 1


def io_retry(fn, description=None):
    """Retry a record/file open with the MXTPU_IO_* env plane.

    Missing files are NOT retried (a local ENOENT is deterministic); any
    other OSError — the flaky-NFS/FUSE class — is.
    """
    retries = int(os.environ.get("MXTPU_IO_RETRIES", "2"))
    backoff = float(os.environ.get("MXTPU_IO_BACKOFF", "0.05"))

    def attempt():
        inject_failure("io_open")
        return fn()

    return retry_call(attempt, retries=retries, backoff=backoff,
                      retryable=(OSError, InjectedFault),
                      non_retryable=(FileNotFoundError,),
                      description=description or "io open")


# -- watchdog ------------------------------------------------------------------

def dump_thread_stacks(stream=None, reason=""):
    """Write every Python thread's current stack to ``stream`` (stderr).

    The post-mortem for a wedged process: WHERE each thread is blocked,
    not just that it is.
    """
    stream = stream or sys.stderr
    names = {t.ident: t.name for t in threading.enumerate()}
    lines = [f"==== thread stack dump"
             f"{' (' + reason + ')' if reason else ''} ====\n"]
    for ident, frame in sys._current_frames().items():
        lines.append(f"--- thread {names.get(ident, '?')} "
                     f"(ident {ident}) ---\n")
        lines.extend(traceback.format_stack(frame))
    lines.append("==== end stack dump ====\n")
    try:
        stream.write("".join(lines))
        stream.flush()
    except Exception:
        pass


class Watchdog:
    """Heartbeat watchdog armed around blocking device work.

    ::

        with Watchdog(60, name="allreduce"):
            kv.pushpull(...)          # raises WatchdogExpired if > 60s

    On expiry the watchdog thread dumps all Python thread stacks, calls
    ``on_expire`` (if given), then applies ``action``:

    - ``"interrupt"``: raise in the main thread (via interrupt_main).
      Breaks python-level blocking (sleep, socket waits); a C call that
      never returns to the interpreter will NOT see it.
    - ``"abort"``: ``os._exit(exit_code)`` — the only reliable escape
      from a wedged C extension call (the tunnel-wedge failure mode).
      The stack dump has already landed on ``stream`` by then.
    - ``"none"``: only dump + ``on_expire`` (e.g. kill a child process
      the caller is ``communicate()``-ing with).

    ``feed()`` resets the deadline (heartbeat); ``cancel()`` disarms.
    """

    def __init__(self, timeout, name="watchdog", action=None,
                 on_expire=None, exit_code=None, stream=None,
                 dump_stacks=True):
        self.timeout = float(timeout)
        self.name = name
        self.action = action or os.environ.get(
            "MXTPU_WATCHDOG_ACTION", "interrupt")
        if self.action not in ("interrupt", "abort", "none"):
            raise MXNetError(f"Watchdog: unknown action {self.action!r}")
        self.on_expire = on_expire
        self.exit_code = int(
            os.environ.get("MXTPU_WATCHDOG_EXIT_CODE", 124)
            if exit_code is None else exit_code)
        self.stream = stream
        self.dump_stacks = dump_stacks
        self.expired = False
        self._deadline = None
        self._wake = threading.Event()
        self._cancelled = False
        self._thread = None

    # -- lifecycle -------------------------------------------------------------
    def start(self):
        if self._thread is not None:
            return self
        self._deadline = time.monotonic() + self.timeout
        self._thread = threading.Thread(
            target=self._watch, name=f"watchdog:{self.name}", daemon=True)
        self._thread.start()
        return self

    def feed(self):
        """Heartbeat: push the deadline out by ``timeout`` from now."""
        self._deadline = time.monotonic() + self.timeout

    def cancel(self):
        self._cancelled = True
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=1.0)

    def _watch(self):
        while not self._cancelled:
            remaining = self._deadline - time.monotonic()
            if remaining > 0:
                self._wake.wait(timeout=remaining)
                continue
            # deadline passed without a feed/cancel
            self.expired = True
            stream = self.stream or sys.stderr
            try:
                stream.write(
                    f"[resilience] watchdog '{self.name}' expired after "
                    f"{self.timeout:.1f}s (action={self.action})\n")
                stream.flush()
            except Exception:
                pass
            try:
                _tel_event("watchdog_expired", name=self.name,
                           timeout_s=self.timeout, action=self.action)
            except Exception:
                pass
            if self.dump_stacks:
                dump_thread_stacks(stream,
                                   reason=f"watchdog {self.name}")
            if self.on_expire is not None:
                try:
                    self.on_expire()
                except Exception:
                    traceback.print_exc()
            if self.action == "abort":
                os._exit(self.exit_code)
            elif self.action == "interrupt":
                # pthread_kill EINTRs a main thread blocked in a syscall
                # (time.sleep, socket waits) — interrupt_main() alone only
                # sets a flag checked at the NEXT bytecode, which a
                # blocking call never reaches
                try:
                    signal.pthread_kill(threading.main_thread().ident,
                                        signal.SIGINT)
                except (AttributeError, ValueError, OSError):
                    import _thread

                    _thread.interrupt_main()
            return

    # -- context manager -------------------------------------------------------
    def __enter__(self):
        return self.start()

    def __exit__(self, exc_type, exc, tb):
        self.cancel()
        if self.expired and self.action == "interrupt":
            # translate the injected KeyboardInterrupt (or whatever it
            # landed in) into a structured error
            raise WatchdogExpired(
                f"'{self.name}' exceeded {self.timeout:.1f}s watchdog "
                f"deadline (thread stacks dumped)") from exc
        return False


@contextlib.contextmanager
def _env_watchdog(env_var, name):
    """Arm a Watchdog if the env var sets a timeout; no-op otherwise."""
    timeout = os.environ.get(env_var)
    if not timeout:
        yield None
        return
    with Watchdog(float(timeout), name=name) as wd:
        yield wd


@contextlib.contextmanager
def guard_collective(name="collective"):
    """Guard an eager cross-process collective (kvstore all-reduce,
    distributed.barrier): watchdog from MXTPU_COLLECTIVE_TIMEOUT plus the
    ``stall_collective`` fault-injection point."""
    with _env_watchdog("MXTPU_COLLECTIVE_TIMEOUT", name):
        maybe_stall("stall_collective")
        yield


@contextlib.contextmanager
def guard_step(name="train_step"):
    """Guard one compiled-step dispatch (MXTPU_STEP_TIMEOUT)."""
    with _env_watchdog("MXTPU_STEP_TIMEOUT", name):
        yield


@contextlib.contextmanager
def guard_checkpoint(name="checkpoint"):
    """Guard a checkpoint save/restore (MXTPU_CKPT_TIMEOUT, unset = off):
    a hung filesystem dumps every thread's stack instead of wedging the
    run silently."""
    with _env_watchdog("MXTPU_CKPT_TIMEOUT", name):
        yield


# -- local checkpointer --------------------------------------------------------

_CKPT_MAGIC = b"MXTCKPT1"


class LocalCheckpointer:
    """Single-host checkpoints with CRC-verified atomic writes.

    The same save/restore/latest_step/all_steps/wait surface as
    ``checkpoint.ShardedCheckpointer`` so :func:`run_resilient` composes
    with either; this one needs no orbax/jax and is what the hermetic
    fault tests (and single-host users) run.

    Format: ``MXTCKPT1 | crc32:u32 | length:u64 | pickle(state)`` written
    to a temp file and atomically renamed — a crash mid-write can never
    leave a half-written file under a valid name, and a corrupt/partial
    file fails closed via the checksum.
    """

    def __init__(self, directory, max_to_keep=3):
        self._dir = os.path.abspath(directory)
        os.makedirs(self._dir, exist_ok=True)
        self.max_to_keep = max_to_keep

    def _path(self, step):
        return os.path.join(self._dir, f"ckpt_{int(step):010d}.mxtckpt")

    @staticmethod
    def _to_host(state):
        """Device arrays pickle as numpy (a restored checkpoint must not
        depend on the dying process's device layout)."""
        import numpy as np

        def conv(v):
            if isinstance(v, dict):
                return {k: conv(x) for k, x in v.items()}
            if isinstance(v, (list, tuple)):
                out = [conv(x) for x in v]
                return out if isinstance(v, list) else tuple(out)
            if hasattr(v, "__array__"):
                return np.asarray(v)
            return v

        return conv(state)

    def save(self, step, state):
        payload = pickle.dumps(self._to_host(state), protocol=4)
        header = _CKPT_MAGIC + struct.pack(
            "<IQ", zlib.crc32(payload) & 0xffffffff, len(payload))
        tmp = self._path(step) + ".tmp"
        with guard_checkpoint(f"ckpt_save:{step}"):
            with open(tmp, "wb") as f:
                f.write(header)
                f.write(payload)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self._path(step))
            # durability: the rename lives in the directory inode — fsync
            # it too, or power loss can roll the commit back
            fsync_dir(self._dir)
        self._prune()
        return step

    def _prune(self):
        steps = self.all_steps()
        for s in steps[:-self.max_to_keep] if self.max_to_keep else []:
            try:
                os.remove(self._path(s))
            except OSError:
                pass

    def restore(self, step=None, template=None):
        if step is None:
            step = self.latest_step()
            if step is None:
                raise MXNetError(f"no checkpoints under {self._dir}")
        path = self._path(step)

        def read():
            with open(path, "rb") as f:
                return f.read()

        with guard_checkpoint(f"ckpt_restore:{step}"):
            blob = io_retry(read, description=f"read {path}")
        if len(blob) < len(_CKPT_MAGIC) + 12 or \
                not blob.startswith(_CKPT_MAGIC):
            raise CheckpointCorrupt(f"{path}: bad checkpoint magic")
        crc, length = struct.unpack(
            "<IQ", blob[len(_CKPT_MAGIC):len(_CKPT_MAGIC) + 12])
        payload = blob[len(_CKPT_MAGIC) + 12:]
        if len(payload) != length:
            raise CheckpointCorrupt(
                f"{path}: truncated (want {length} payload bytes, have "
                f"{len(payload)})")
        if zlib.crc32(payload) & 0xffffffff != crc:
            raise CheckpointCorrupt(f"{path}: checksum mismatch")
        return pickle.loads(payload)

    def verify(self, step):
        """Re-read and checksum a written checkpoint (verify-after-write).
        Raises CheckpointCorrupt on any mismatch."""
        self.restore(step)

    def all_steps(self):
        steps = []
        for name in os.listdir(self._dir):
            if name.startswith("ckpt_") and name.endswith(".mxtckpt"):
                try:
                    steps.append(int(name[5:-8]))
                except ValueError:
                    pass
        return sorted(steps)

    def latest_step(self):
        steps = self.all_steps()
        return steps[-1] if steps else None

    def wait(self):
        pass

    def close(self):
        pass


# -- resilient training driver -------------------------------------------------

class RunReport:
    """What :func:`run_resilient` did: where it resumed, how many
    restarts it burned, and the per-step loss trajectory."""

    def __init__(self):
        self.final_step = 0
        self.restarts = 0
        self.resumed_from = []   # checkpoint step of each (re)start
        self.losses = {}         # step -> float loss
        self.preempted = False

    def __repr__(self):
        return (f"RunReport(final_step={self.final_step}, "
                f"restarts={self.restarts}, "
                f"resumed_from={self.resumed_from}, "
                f"preempted={self.preempted})")


def flush_inflight(checkpointer, logger=None):
    """Drain an async checkpointer's in-flight save at a recovery point.

    A failed background commit must not abort recovery — the previous
    checkpoint is still valid, which is the whole point of the two-phase
    commit — so errors are logged and swallowed here (they would have
    been raised from the next ``save()`` anyway).
    """
    wait = getattr(checkpointer, "wait", None)
    if wait is None:
        return
    try:
        wait()
    except Exception as e:                      # noqa: BLE001
        _log(logger, f"in-flight checkpoint save failed ({e}); "
                     f"recovering from the previous checkpoint")


def resume_latest(checkpointer, set_state, logger=None):
    """Restore the newest VALID checkpoint; corrupt/partial ones fall
    back to the previous step.  Returns the restored step (0 = fresh).
    Any in-flight async save is drained first so a commit racing the
    restore can't be half-observed."""
    flush_inflight(checkpointer, logger)
    steps = sorted(checkpointer.all_steps(), reverse=True) \
        if hasattr(checkpointer, "all_steps") else \
        ([checkpointer.latest_step()]
         if checkpointer.latest_step() is not None else [])
    for step in steps:
        try:
            state = checkpointer.restore(step)
        except Exception as e:
            _log(logger, f"checkpoint step {step} unreadable ({e}); "
                         f"falling back to the previous one")
            continue
        set_state(state)
        _log(logger, f"resumed from checkpoint step {step}")
        return step
    return 0


def _log(logger, msg):
    if logger is None:
        sys.stderr.write(f"[resilience] {msg}\n")
    else:
        logger.info(msg)


def _save_verified(checkpointer, step, state, logger=None):
    """Save + verify-after-write; one rewrite attempt on a bad readback."""
    for attempt in range(2):
        checkpointer.save(step, state)
        checkpointer.wait()
        verify = getattr(checkpointer, "verify", None)
        if verify is None:
            return
        try:
            verify(step)
            return
        except CheckpointCorrupt as e:
            if attempt:
                raise
            _log(logger, f"checkpoint step {step} failed verification "
                         f"({e}); rewriting once")


def run_resilient(step_fn, checkpointer, num_steps, *, get_state,
                  set_state, checkpoint_every=None, max_restarts=3,
                  watchdog_timeout=None, exit_on_preempt=False,
                  recover_on=(RuntimeError, OSError), logger=None):
    """Supervised training loop: auto-resume + preemption checkpointing +
    bounded in-process restarts.

    - ``step_fn(step) -> loss``: run ONE training step (0-based ``step``
      counts completed steps).  Must be a pure function of the current
      training state for crash-resume to reproduce the loss trajectory.
    - ``get_state() -> pytree`` / ``set_state(pytree)``: snapshot/load
      everything a restart needs (params, optimizer state, RNG, ...).
    - ``checkpointer``: LocalCheckpointer / checkpoint.AsyncCheckpointer /
      ShardedCheckpointer surface.  An async engine overlaps the
      serialize+fsync with training (its CRC-verified two-phase commit
      replaces the synchronous verify-after-write) and is drained at
      every recovery point and at the end of the run.
    - ``checkpoint_every``: steps between periodic saves; ``None`` reads
      ``MXTPU_CKPT_EVERY`` (default 25), ``0`` disables.
    - On SIGTERM (TPU preemption notice) the current state is
      checkpointed; with ``exit_on_preempt`` the driver returns (the
      process is about to die), otherwise the preemption is treated as
      an in-process restart and counted against ``max_restarts`` — the
      hermetic analog of kill-and-relaunch.
    - A step failure in ``recover_on`` (or a watchdog expiry) restores
      the latest valid checkpoint and replays; corrupt checkpoints fall
      back to the previous step.

    Returns a :class:`RunReport`.
    """
    from .checkpoint import PreemptionHandler

    if checkpoint_every is None:
        checkpoint_every = int(os.environ.get("MXTPU_CKPT_EVERY", 25))
    # async engines own crash consistency via the two-phase commit; the
    # synchronous readback verify would serialize the save we just made
    # asynchronous
    is_async = bool(getattr(checkpointer, "async_save", False))

    def save_at(step):
        if is_async:
            checkpointer.save(step, get_state())
        else:
            _save_verified(checkpointer, step, get_state(), logger)

    report = RunReport()
    step = resume_latest(checkpointer, set_state, logger)
    report.resumed_from.append(step)
    _tel_event("resume", step=step)
    last_saved = step
    step_box = [step]
    with PreemptionHandler(checkpointer, get_state,
                           lambda: step_box[0]) as handler:
        while step < num_steps:
            step_box[0] = step
            # fault injection: deliver a real SIGTERM to ourselves at
            # step S — exercises the whole preemption path
            if fault_arg("sigterm_at_step") == step and \
                    consume_fault("sigterm_at_step"):
                os.kill(os.getpid(), signal.SIGTERM)
            if handler.preempted.is_set():
                handler.maybe_checkpoint()   # saves at current step
                last_saved = step
                report.preempted = True
                if exit_on_preempt:
                    report.final_step = step
                    return report
                if report.restarts >= max_restarts:
                    raise MXNetError(
                        f"run_resilient: preempted with no restarts left "
                        f"(max_restarts={max_restarts})")
                report.restarts += 1
                handler.preempted.clear()
                step = resume_latest(checkpointer, set_state, logger)
                report.resumed_from.append(step)
                _tel_event("restart", step=step, reason="preempted")
                continue
            try:
                if watchdog_timeout:
                    with Watchdog(watchdog_timeout,
                                  name=f"step {step}"):
                        loss = step_fn(step)
                else:
                    loss = step_fn(step)
            except recover_on as e:
                if report.restarts >= max_restarts:
                    raise
                report.restarts += 1
                _log(logger, f"step {step} failed ({type(e).__name__}: "
                             f"{e}); restart "
                             f"{report.restarts}/{max_restarts}")
                reason = type(e).__name__
                step = resume_latest(checkpointer, set_state, logger)
                report.resumed_from.append(step)
                _tel_event("restart", step=step, reason=reason)
                continue
            if loss is not None:
                try:
                    report.losses[step] = float(loss)
                except (TypeError, ValueError):
                    pass
            step += 1
            if checkpoint_every and step % checkpoint_every == 0:
                save_at(step)
                last_saved = step
        if step > last_saved:
            save_at(step)
        if is_async:
            checkpointer.wait()   # the final commit must land before we
    report.final_step = step      # report the run finished
    return report
