"""Fault-tolerance layer: retries, watchdogs, resilient training driver.

SURVEY §5.3 names checkpoint-restart as the recovery primitive for
multi-host TPU training; the failure modes this module covers are the
runtime ones that actually occur on shared TPU pools: preemption
(SIGTERM with a grace window), coordinator unreachability at rendezvous,
corrupt/truncated records on network storage, and stalled ICI/DCN
collectives that otherwise hang a process forever (the round-5 tunnel
wedge).

Four primitives, composed by the rest of the stack:

- :func:`retry_call` — exponential backoff with jitter, the single retry
  primitive behind rendezvous (``distributed.py``) and file opens
  (``recordio.py`` / ``io/io.py``).
- :class:`Watchdog` — a heartbeat thread armed around blocking device
  work (step dispatch, cross-process all-reduce, ``distributed.barrier``,
  the bench backend probe).  On expiry it dumps every Python thread's
  stack and then interrupts or aborts instead of hanging forever.
- :func:`run_resilient` — a supervised training driver composing
  ``checkpoint.PreemptionHandler`` + auto-resume-from-latest-checkpoint
  + bounded in-process restarts, with verify-after-write checkpoint
  validation and fallback to the previous checkpoint when the latest is
  corrupt or partial.
- ``MXTPU_FAULT_INJECT`` — a fault-injection env contract so every
  recovery path above is testable hermetically on CPU.

Env plane (matching storage.py's env-var style):

==============================  ================================================
``MXTPU_RENDEZVOUS_TIMEOUT``    total seconds to keep retrying rendezvous (300)
``MXTPU_RENDEZVOUS_RETRIES``    max rendezvous attempts - 1 (3)
``MXTPU_IO_RETRIES``            retries for record/file opens (2)
``MXTPU_IO_BACKOFF``            base backoff seconds for IO retries (0.05)
``MXTPU_COLLECTIVE_TIMEOUT``    watchdog seconds around eager collectives
                                (unset = no watchdog)
``MXTPU_STEP_TIMEOUT``          watchdog seconds around compiled step dispatch
                                (unset = no watchdog)
``MXTPU_WATCHDOG_ACTION``       ``interrupt`` (default) or ``abort`` — abort is
                                the only escape from a wedged C call
``MXTPU_WATCHDOG_EXIT_CODE``    process exit code for ``abort`` (124)
``MXTPU_FAULT_INJECT``          comma list of ``site[:arg]`` fault specs
==============================  ================================================

Fault-injection sites (``MXTPU_FAULT_INJECT="site:arg,site:arg"``):

- ``rendezvous:N``      — fail the next N rendezvous attempts
- ``io_open:N``         — fail the next N record/file opens
- ``corrupt_record:K``  — the K-th record a reader returns reads as corrupt
- ``sigterm_at_step:S`` — deliver SIGTERM to this process at step S
                          (honored by :func:`run_resilient`)
- ``stall_collective[:SECS]`` — stall inside the next guarded collective
                          (default 3600s — the watchdog must fire first)
- ``crash_during_save``  — hard-kill the process mid-shard-write (the
                          async checkpoint engine, checkpoint.py)
- ``crash_before_manifest`` — hard-kill after all shards are written but
                          before the manifest commit rename
- ``corrupt_shard:K``    — flip bytes in shard K of the checkpoint that
                          was just committed
- ``corrupt_ckpt_write:N`` — bit-rot the next N committed
                          LocalCheckpointer files (verify-after-write
                          must catch them)
- ``kill_rank:K``        — SIGKILL this process when it IS gang rank K
                          (optionally gated on ``MXTPU_KILL_AT_STEP``);
                          repeatable: ``kill_rank:1,kill_rank:2``
- ``slow_rank:K``        — rank K sleeps ``MXTPU_SLOW_RANK_SECS`` per
                          step tick (straggler injection)
- ``heartbeat_loss:K``   — rank K stops publishing heartbeats while the
                          process keeps running (the wedged-alive mode)
- ``corrupt_tune_db:N``  — bit-rot the next N tuning-DB entries as they
                          are written (autotune/db.py; readers must fall
                          back to defaults, never crash)
- ``tune_oom:N``         — the next N autotune trials fail with a
                          simulated RESOURCE_EXHAUSTED (the infeasible-
                          point path, hermetic on CPU)
- ``bit_flip_param:K``   — flip one bit in rank K's first parameter
                          after a step commits (memory SDC; one-shot —
                          integrity.py attestation must name rank K)
- ``bit_flip_grad:K``    — flip one bit in rank K's first gradient
                          before the update (eager path, nan_grad
                          routing discipline)
- ``bad_core:K``         — rank K's step input is perturbed so its
                          compute is deterministically wrong (compute
                          SDC; replay audit classifies it)
- ``worker_hang:K``      — the DataLoader worker fetching batch K hangs
                          (``MXTPU_DATA_HANG_SECS``, far past any
                          receive timeout) — the ``MXTPU_DATA_TIMEOUT``
                          watchdog must name the batch, not block
- ``data_skew:K``        — fetches of the first K batches each sleep
                          ``MXTPU_DATA_SKEW_SECS`` (input-skew
                          straggler injection)

Elastic gang recovery (PR 8) also lives here: :class:`HeartbeatPublisher`
/ :class:`FailureDetector` / :class:`StragglerMonitor` form the health
plane over ``distributed.gang_kv()``, and :class:`ElasticGang` runs the
epoch-consensus reshape protocol that lets survivors shrink N→M (and
grow back) without a gang restart.
"""

from __future__ import annotations

import collections
import contextlib
import json
import os
import pickle
import random as _random
import signal
import struct
import sys
import threading
import time
import traceback
import zlib

try:
    from .base import MXNetError
except ImportError:  # loaded standalone (bench.py orchestrator never
    MXNetError = RuntimeError  # imports the package, let alone jax)


class InjectedFault(MXNetError):
    """An error raised by the MXTPU_FAULT_INJECT test harness."""


class WatchdogExpired(MXNetError):
    """Blocking work outlived its Watchdog deadline."""


class CheckpointCorrupt(MXNetError):
    """A checkpoint failed validation (bad magic/length/checksum)."""


def _tel_event(kind, /, **fields):
    """Structured telemetry event, guarded: this module also loads
    standalone (bench.py orchestrator keeps its driver jax-free), where
    the relative import has no package to resolve against."""
    try:
        from . import telemetry
    except ImportError:
        return
    telemetry.event(kind, **fields)


def _tel_identity(rank=None, world=None):
    """Stamp this process's fleet identity onto telemetry records
    (schema v3) — same import guard as _tel_event."""
    try:
        from . import telemetry
    except ImportError:
        return
    telemetry.set_identity(rank=rank, world=world)


def _tel_set_epoch(epoch):
    """Stamp the adopted gang epoch onto telemetry step records
    (schema v8) — same import guard as _tel_event."""
    try:
        from . import telemetry
    except ImportError:
        return
    telemetry.set_gang_epoch(int(epoch))


def _gang_kv_errors():
    """Exception classes that mean 'the gang KV is unreachable from
    this rank' — the fencing trigger.  Resolved lazily because
    `distributed` imports this module."""
    from . import distributed
    return (distributed.GangKVError, OSError)


# -- fault injection -----------------------------------------------------------

class _FaultPlan:
    """Parsed MXTPU_FAULT_INJECT with live counters."""

    def __init__(self, spec):
        self.spec = spec
        self.counts = {}   # site -> remaining trigger count
        self.args = {}     # site -> numeric arg (step index, seconds, ...)
        self.list_args = {}  # site -> [rank, ...] (repeatable rank sites)
        self.partition_started = None  # monotonic t of first blocked op
        self.partition_healed = False  # heal announced (telemetry, once)
        for item in (spec or "").split(","):
            item = item.strip()
            if not item:
                continue
            site, _, arg = item.partition(":")
            if site in ("rendezvous", "io_open", "nan_grad", "inf_loss",
                        "crash_during_save", "crash_before_manifest",
                        "telemetry_crash", "telemetry_rotate",
                        "corrupt_ckpt_write",
                        "kill_coordinator", "corrupt_tune_db",
                        "tune_oom"):
                # telemetry_rotate: crash between the telemetry sink's
                # rename-to-.1 and the reopen (telemetry._rotate_locked)
                # — the torn-rotation window readers must survive
                # corrupt_tune_db: bit-rot the next N tuning-DB entry
                # lines as they are written (autotune/db.record) — the
                # CRC check must read them as absent, never crash;
                # tune_oom: the next N autotune trials raise a
                # RESOURCE_EXHAUSTED (autotune/runner.run_trial) and
                # must score infeasible
                # kill_coordinator: the gang KV daemon
                # (distributed.GangKVServer) drops dead on the Nth
                # mutation — mid-protocol, no reply, connections cut —
                # exercising the TcpKV client failover path
                # nan_grad: poison one gradient with NaN before health
                # assessment (consumed by the Trainer's numerics guard);
                # inf_loss: corrupt the loss seen by
                # numerics.DivergenceMonitor.observe;
                # telemetry_crash: kill the process mid-JSONL-append
                # (telemetry._emit) to prove the log stays parseable;
                # corrupt_ckpt_write: bit-rot the next N committed
                # LocalCheckpointer files (verify-after-write coverage)
                self.counts[site] = int(arg) if arg else 1
            elif site in ("corrupt_record", "sigterm_at_step",
                          "corrupt_shard", "worker_hang", "data_skew"):
                # worker_hang: the loader worker fetching batch K
                # sleeps MXTPU_DATA_HANG_SECS (one-shot) — exercises
                # the MXTPU_DATA_TIMEOUT receive watchdog;
                # data_skew: fetches of the first K batches each sleep
                # MXTPU_DATA_SKEW_SECS (persistent input straggler)
                self.args[site] = int(arg) if arg else 0
                self.counts[site] = 1
            elif site in ("kill_rank", "slow_rank", "heartbeat_loss",
                          "net_partition", "partition_split"):
                # rank-targeted sites: repeatable ("kill_rank:1,
                # kill_rank:2"), persistent conditions (no counter) —
                # each process checks its OWN gang rank against the
                # list.  net_partition:K cuts rank K's TcpKV client off
                # from the coordinator (every op raises GangKVError)
                # while the process keeps running.
                # partition_split:K is the ASYMMETRIC variant: listed
                # ranks (the minority group) get net_partition-style
                # timeouts on every gang-KV op while unlisted ranks
                # keep full connectivity; the cut HEALS after
                # MXTPU_PARTITION_SECS (measured from the first blocked
                # op), after which the fenced minority can rejoin
                self.list_args.setdefault(site, []).append(
                    int(arg) if arg else 0)
            elif site in ("bit_flip_param", "bit_flip_grad",
                          "bad_core", "pause_rank"):
                # silent-data-corruption sites (integrity.py): rank-
                # targeted like kill_rank, but ONE-SHOT per listed rank
                # — bit_flip_param:K flips one bit in rank K's first
                # parameter after a step commits (memory SDC);
                # bit_flip_grad:K flips one bit in a gradient before
                # the update (eager path only, nan_grad routing);
                # bad_core:K perturbs rank K's step input so its
                # compute is deterministically wrong (compute SDC);
                # pause_rank:K SIGSTOPs rank K's process for
                # MXTPU_PAUSE_SECS then SIGCONTs it (one-shot) — the
                # zombie-rank scenario: suspended across a reshape,
                # resumed after its own eviction
                r = int(arg) if arg else 0
                self.list_args.setdefault(site, []).append(r)
                self.counts[f"{site}:{r}"] = 1
            elif site in ("stall_collective", "stall"):
                self.args["stall_collective"] = float(arg) if arg else 3600.0
                self.counts["stall_collective"] = 1
            else:
                raise MXNetError(
                    f"MXTPU_FAULT_INJECT: unknown site {site!r} in "
                    f"{spec!r}")

    def consume(self, site):
        """True (and decrements) while the site still has failures left."""
        n = self.counts.get(site, 0)
        if n <= 0:
            return False
        self.counts[site] = n - 1
        return True

    def arg(self, site):
        return self.args.get(site)


_PLAN = None
_PLAN_SPEC = None
_PLAN_LOCK = threading.Lock()


def _plan():
    """The plan for the CURRENT env value; counters persist while the env
    is unchanged, and a change (tests flipping the fixture) re-parses."""
    global _PLAN, _PLAN_SPEC
    spec = os.environ.get("MXTPU_FAULT_INJECT")
    with _PLAN_LOCK:
        if spec != _PLAN_SPEC:
            _PLAN = _FaultPlan(spec) if spec else None
            _PLAN_SPEC = spec
        return _PLAN


def reset_faults():
    """Drop cached injection counters (the `faults` conftest fixture)."""
    global _PLAN, _PLAN_SPEC
    with _PLAN_LOCK:
        _PLAN = None
        _PLAN_SPEC = None


def inject_failure(site):
    """Raise InjectedFault if the site has injected failures remaining."""
    plan = _plan()
    if plan is not None and plan.consume(site):
        raise InjectedFault(f"injected {site} failure "
                            f"(MXTPU_FAULT_INJECT={plan.spec})")


def fault_arg(site):
    """The numeric argument of an armed site, or None (does not consume)."""
    plan = _plan()
    return None if plan is None else plan.arg(site)


def fault_args(site):
    """All arguments of a repeatable rank-targeted site (kill_rank /
    slow_rank / heartbeat_loss), as a tuple; empty when unarmed."""
    plan = _plan()
    return () if plan is None else tuple(plan.list_args.get(site, ()))


def consume_fault(site):
    """True once per armed count for the site (non-raising variant)."""
    plan = _plan()
    return plan is not None and plan.consume(site)


def fault_armed(site):
    """True while the site still has injected failures pending (does NOT
    consume).  Lets a fast path that cannot express a site's fault —
    e.g. the captured train step, whose gradients never materialize for
    ``nan_grad`` poisoning — route the affected step to the path that
    can.  Rank-targeted sites keep their one-shot charges under
    ``site:rank`` keys — armed while ANY listed rank's charge is
    unspent."""
    plan = _plan()
    if plan is None:
        return False
    if plan.counts.get(site, 0) > 0:
        return True
    prefix = site + ":"
    return any(v > 0 for k, v in plan.counts.items()
               if k.startswith(prefix))


def consume_rank_fault(site, rank):
    """One-shot rank-targeted charge: True exactly once for each rank
    listed on the site (``bit_flip_param:1`` fires once on rank 1,
    never again, never on anyone else).  The per-rank charge lives in
    the same counter table as counted sites, keyed ``site:rank``."""
    if rank not in fault_args(site):
        return False
    plan = _plan()
    return plan is not None and plan.consume(f"{site}:{int(rank)}")


def consume_charges(site, on_last=True):
    """Shared charge-consumption semantics for counted sites.

    Consumes ONE charge of ``site`` (when any remain) and reports
    whether the fault should FIRE now:

    - ``on_last=True`` (kill_coordinator semantics, the PR 11 off-by
      fix): the fault fires on the LAST charge only — ``site:N`` means
      "survive N-1 occurrences, die on the Nth".  Returns True when
      the charge just consumed was the final one.
    - ``on_last=False`` (corrupt_ckpt_write / corrupt_shard
      semantics): every charge fires — ``site:N`` corrupts the next N
      occurrences.  Returns True for each consumed charge.
    """
    plan = _plan()
    if plan is None or not plan.consume(site):
        return False
    if not on_last:
        return True
    return plan.counts.get(site, 0) <= 0


#: exit code of an injected hard crash (``crash_during_save`` /
#: ``crash_before_manifest``) — distinct from the watchdog's 124 so the
#: crash-consistency tests can assert WHICH kill fired.
CRASH_EXIT_CODE = 57


def maybe_crash(site):
    """Injected hard crash: ``os._exit`` with no cleanup, no atexit, no
    flush — the closest a test can get to power loss / OOM-kill."""
    plan = _plan()
    if plan is not None and plan.consume(site):
        sys.stderr.write(f"[resilience] injected crash at {site}\n")
        sys.stderr.flush()
        os._exit(CRASH_EXIT_CODE)


def maybe_stall(site="stall_collective"):
    """Injected stall: sleep in small interruptible increments so an
    'interrupt' watchdog can break the stall (a real wedged C collective
    needs action='abort'; see Watchdog)."""
    plan = _plan()
    if plan is None or not plan.consume("stall_collective"):
        return
    seconds = plan.arg("stall_collective") or 3600.0
    deadline = time.monotonic() + seconds
    while time.monotonic() < deadline:
        time.sleep(0.05)


def maybe_data_fault(batch_idx):
    """Input-pipeline fault sites, keyed by BATCH index, called from the
    loader worker fetching that batch (thread transport; spawn workers
    run the stdlib mirror in ``gluon/data/_shm_worker.py``):

    - ``worker_hang:K`` — the fetch of batch K sleeps
      ``MXTPU_DATA_HANG_SECS`` (default 10, bounded so interpreter
      teardown can't deadlock on the worker), one-shot.  Far past any
      sane ``MXTPU_DATA_TIMEOUT``, so the receive watchdog fires first.
    - ``data_skew:K`` — fetches of batches 0..K-1 each sleep
      ``MXTPU_DATA_SKEW_SECS`` (default 0.05); persistent, never
      consumed (straggler-style input skew).
    """
    k = fault_arg("worker_hang")
    if k is not None and int(k) == int(batch_idx) and \
            consume_fault("worker_hang"):
        time.sleep(float(os.environ.get("MXTPU_DATA_HANG_SECS", 10.0)))
        return
    k = fault_arg("data_skew")
    if k is not None and int(batch_idx) < int(k):
        time.sleep(float(os.environ.get("MXTPU_DATA_SKEW_SECS", 0.05)))


def maybe_kill_rank(rank, step=None):
    """``kill_rank:K``: SIGKILL this process when its gang rank is K —
    no cleanup, no atexit, no SIGTERM grace.  ``MXTPU_KILL_AT_STEP``
    (when set AND a step is supplied) gates the kill to one exact step,
    so the multi-process tests control precisely which snapshots exist
    when the rank dies."""
    if rank not in fault_args("kill_rank"):
        return
    at = os.environ.get("MXTPU_KILL_AT_STEP")
    if at is not None and step is not None and int(at) != int(step):
        return
    sys.stderr.write(f"[resilience] injected kill_rank: SIGKILL rank "
                     f"{rank} at step {step}\n")
    sys.stderr.flush()
    os.kill(os.getpid(), signal.SIGKILL)


def maybe_slow_rank(rank):
    """``slow_rank:K``: rank K sleeps MXTPU_SLOW_RANK_SECS (0.2) per
    step tick — a persistent straggler the StragglerMonitor must name."""
    if rank in fault_args("slow_rank"):
        time.sleep(float(os.environ.get("MXTPU_SLOW_RANK_SECS", "0.2")))


def partition_blocked(rank):
    """``partition_split:K``: True while rank K's side of the injected
    asymmetric partition is cut off from the gang KV.  The cut heals
    ``MXTPU_PARTITION_SECS`` (default 0 = never) after the FIRST blocked
    op, so one plan expresses the whole partition lifecycle: minority
    fences, majority reshapes, minority rejoins after the heal.  Checked
    by the KV transports (``FileKV`` / ``TcpKV``), which raise
    ``GangKVError`` while blocked."""
    plan = _plan()
    if plan is None or rank not in plan.list_args.get(
            "partition_split", ()):
        return False
    now = time.monotonic()
    with _PLAN_LOCK:
        if plan.partition_started is None:
            plan.partition_started = now
        started = plan.partition_started
    try:
        heal_s = float(os.environ.get("MXTPU_PARTITION_SECS", "0"))
    except ValueError:
        heal_s = 0.0
    if heal_s > 0 and now - started >= heal_s:
        return False
    return True


def maybe_pause_rank(rank):
    """``pause_rank:K``: SIGSTOP this process when its gang rank is K
    (one-shot), with a detached helper process sending SIGCONT after
    ``MXTPU_PAUSE_SECS`` (default 3).  The zombie scenario: by resume
    time the gang has reshaped this rank out, and its very next KV
    touch must learn the committed epoch and raise ``GangEvicted``
    before any durable write."""
    if not consume_rank_fault("pause_rank", rank):
        return
    secs = float(os.environ.get("MXTPU_PAUSE_SECS", "3.0"))
    sys.stderr.write(f"[resilience] injected pause_rank: SIGSTOP rank "
                     f"{rank} for {secs}s\n")
    sys.stderr.flush()
    import subprocess

    subprocess.Popen(
        [sys.executable, "-c",
         f"import os, signal, time; time.sleep({secs}); "
         f"os.kill({os.getpid()}, signal.SIGCONT)"],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    os.kill(os.getpid(), signal.SIGSTOP)


# -- durable IO ----------------------------------------------------------------

def fsync_dir(path):
    """fsync a DIRECTORY so a just-renamed entry survives power loss.

    ``os.replace`` makes a write atomic but not durable: the rename
    itself lives in the directory inode, which ``fsync`` of the data
    file never touches.  Both checkpointers call this after every
    rename-commit.  Filesystems that refuse directory fds (some network
    mounts) are tolerated — they journal renames themselves.
    """
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


# -- retry primitive -----------------------------------------------------------

def retry_call(fn, *, retries=3, deadline=None, max_elapsed=None,
               backoff=0.1, max_backoff=5.0, jitter=True,
               retryable=(Exception,), non_retryable=(), on_retry=None,
               description=None):
    """Call ``fn()`` with exponential-backoff-with-jitter retries.

    - ``retries``: max retry count (total attempts = retries + 1)
    - ``max_elapsed``: hard cap on TOTAL elapsed seconds (off by
      default): once a failed attempt finds the budget spent the
      original exception is re-raised — unlike ``deadline`` it cannot
      be overshot by a slow ``fn()`` (e.g. a full connect-timeout per
      attempt during a network partition), which is what lets
      partition-era KV retries fail over to fencing checks instead of
      retrying unboundedly
    - ``deadline``: total wall-clock budget in seconds; a retry whose
      backoff sleep would overshoot the deadline raises instead
    - ``jitter``: on by default — DECORRELATED jitter: each sleep is
      ``uniform(backoff, 3 * previous_sleep)`` capped at ``max_backoff``,
      so N workers retrying after one gang-wide incident (say, every
      survivor re-rendezvousing at once) spread out instead of hammering
      the coordinator in lockstep at the same exponential marks.  Falsy
      disables it (deterministic exponential — what the timing tests
      pin); a float keeps the legacy proportional scheme
      (``exponential * (1 + jitter * U[0,1))``).
    - ``retryable``/``non_retryable``: exception classes to retry / to
      re-raise immediately (non_retryable wins)
    - ``on_retry(attempt, exc, sleep_s)``: observer hook
    """
    what = description or getattr(fn, "__name__", "call")
    start = time.monotonic()
    attempt = 0
    prev_sleep = backoff
    while True:
        try:
            return fn()
        except non_retryable:
            raise
        except retryable as e:
            if attempt >= retries:
                raise
            if max_elapsed is not None and \
                    time.monotonic() - start >= max_elapsed:
                raise MXNetError(
                    f"{what}: retry budget {max_elapsed}s exhausted after "
                    f"{attempt + 1} attempts: {e}") from e
            if jitter is True:
                sleep_s = min(max_backoff, _random.uniform(
                    backoff, max(prev_sleep * 3.0, backoff)))
                prev_sleep = sleep_s
            else:
                sleep_s = min(max_backoff, backoff * (2 ** attempt))
                if jitter:
                    sleep_s *= 1.0 + float(jitter) * _random.random()
            if deadline is not None and \
                    time.monotonic() - start + sleep_s > deadline:
                raise MXNetError(
                    f"{what}: retry deadline {deadline}s exceeded after "
                    f"{attempt + 1} attempts: {e}") from e
            if on_retry is not None:
                on_retry(attempt, e, sleep_s)
            else:
                sys.stderr.write(
                    f"[resilience] {what} failed (attempt {attempt + 1}/"
                    f"{retries + 1}): {e}; retrying in {sleep_s:.2f}s\n")
            time.sleep(sleep_s)
            attempt += 1


def io_retry(fn, description=None):
    """Retry a record/file open with the MXTPU_IO_* env plane.

    Missing files are NOT retried (a local ENOENT is deterministic); any
    other OSError — the flaky-NFS/FUSE class — is.
    """
    retries = int(os.environ.get("MXTPU_IO_RETRIES", "2"))
    backoff = float(os.environ.get("MXTPU_IO_BACKOFF", "0.05"))

    def attempt():
        inject_failure("io_open")
        return fn()

    return retry_call(attempt, retries=retries, backoff=backoff,
                      retryable=(OSError, InjectedFault),
                      non_retryable=(FileNotFoundError,),
                      description=description or "io open")


# -- watchdog ------------------------------------------------------------------

def dump_thread_stacks(stream=None, reason=""):
    """Write every Python thread's current stack to ``stream`` (stderr).

    The post-mortem for a wedged process: WHERE each thread is blocked,
    not just that it is.
    """
    stream = stream or sys.stderr
    names = {t.ident: t.name for t in threading.enumerate()}
    lines = [f"==== thread stack dump"
             f"{' (' + reason + ')' if reason else ''} ====\n"]
    for ident, frame in sys._current_frames().items():
        lines.append(f"--- thread {names.get(ident, '?')} "
                     f"(ident {ident}) ---\n")
        lines.extend(traceback.format_stack(frame))
    lines.append("==== end stack dump ====\n")
    try:
        stream.write("".join(lines))
        stream.flush()
    except Exception:
        pass


class Watchdog:
    """Heartbeat watchdog armed around blocking device work.

    ::

        with Watchdog(60, name="allreduce"):
            kv.pushpull(...)          # raises WatchdogExpired if > 60s

    On expiry the watchdog thread dumps all Python thread stacks, calls
    ``on_expire`` (if given), then applies ``action``:

    - ``"interrupt"``: raise in the main thread (via interrupt_main).
      Breaks python-level blocking (sleep, socket waits); a C call that
      never returns to the interpreter will NOT see it.
    - ``"abort"``: ``os._exit(exit_code)`` — the only reliable escape
      from a wedged C extension call (the tunnel-wedge failure mode).
      The stack dump has already landed on ``stream`` by then.
    - ``"none"``: only dump + ``on_expire`` (e.g. kill a child process
      the caller is ``communicate()``-ing with).

    ``feed()`` resets the deadline (heartbeat); ``cancel()`` disarms.
    """

    def __init__(self, timeout, name="watchdog", action=None,
                 on_expire=None, exit_code=None, stream=None,
                 dump_stacks=True):
        self.timeout = float(timeout)
        self.name = name
        self.action = action or os.environ.get(
            "MXTPU_WATCHDOG_ACTION", "interrupt")
        if self.action not in ("interrupt", "abort", "none"):
            raise MXNetError(f"Watchdog: unknown action {self.action!r}")
        self.on_expire = on_expire
        self.exit_code = int(
            os.environ.get("MXTPU_WATCHDOG_EXIT_CODE", 124)
            if exit_code is None else exit_code)
        self.stream = stream
        self.dump_stacks = dump_stacks
        self.expired = False
        self._deadline = None
        self._wake = threading.Event()
        self._cancelled = False
        self._thread = None

    # -- lifecycle -------------------------------------------------------------
    def start(self):
        if self._thread is not None:
            return self
        self._deadline = time.monotonic() + self.timeout
        self._thread = threading.Thread(
            target=self._watch, name=f"watchdog:{self.name}", daemon=True)
        self._thread.start()
        return self

    def feed(self):
        """Heartbeat: push the deadline out by ``timeout`` from now."""
        self._deadline = time.monotonic() + self.timeout

    def cancel(self):
        self._cancelled = True
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=1.0)

    def _watch(self):
        while not self._cancelled:
            remaining = self._deadline - time.monotonic()
            if remaining > 0:
                self._wake.wait(timeout=remaining)
                continue
            # deadline passed without a feed/cancel
            self.expired = True
            stream = self.stream or sys.stderr
            try:
                stream.write(
                    f"[resilience] watchdog '{self.name}' expired after "
                    f"{self.timeout:.1f}s (action={self.action})\n")
                stream.flush()
            except Exception:
                pass
            try:
                _tel_event("watchdog_expired", name=self.name,
                           timeout_s=self.timeout, action=self.action)
            except Exception:
                pass
            if self.dump_stacks:
                dump_thread_stacks(stream,
                                   reason=f"watchdog {self.name}")
            if self.on_expire is not None:
                try:
                    self.on_expire()
                except Exception:
                    traceback.print_exc()
            if self.action == "abort":
                os._exit(self.exit_code)
            elif self.action == "interrupt":
                # pthread_kill EINTRs a main thread blocked in a syscall
                # (time.sleep, socket waits) — interrupt_main() alone only
                # sets a flag checked at the NEXT bytecode, which a
                # blocking call never reaches
                try:
                    signal.pthread_kill(threading.main_thread().ident,
                                        signal.SIGINT)
                except (AttributeError, ValueError, OSError):
                    import _thread

                    _thread.interrupt_main()
            return

    # -- context manager -------------------------------------------------------
    def __enter__(self):
        return self.start()

    def __exit__(self, exc_type, exc, tb):
        self.cancel()
        if self.expired and self.action == "interrupt":
            # translate the injected KeyboardInterrupt (or whatever it
            # landed in) into a structured error
            raise WatchdogExpired(
                f"'{self.name}' exceeded {self.timeout:.1f}s watchdog "
                f"deadline (thread stacks dumped)") from exc
        return False


@contextlib.contextmanager
def _env_watchdog(env_var, name):
    """Arm a Watchdog if the env var sets a timeout; no-op otherwise."""
    timeout = os.environ.get(env_var)
    if not timeout:
        yield None
        return
    with Watchdog(float(timeout), name=name) as wd:
        yield wd


@contextlib.contextmanager
def guard_collective(name="collective"):
    """Guard an eager cross-process collective (kvstore all-reduce,
    distributed.barrier): watchdog from MXTPU_COLLECTIVE_TIMEOUT plus the
    ``stall_collective`` fault-injection point."""
    with _env_watchdog("MXTPU_COLLECTIVE_TIMEOUT", name):
        maybe_stall("stall_collective")
        yield


@contextlib.contextmanager
def guard_step(name="train_step"):
    """Guard one compiled-step dispatch (MXTPU_STEP_TIMEOUT)."""
    with _env_watchdog("MXTPU_STEP_TIMEOUT", name):
        yield


@contextlib.contextmanager
def guard_checkpoint(name="checkpoint"):
    """Guard a checkpoint save/restore (MXTPU_CKPT_TIMEOUT, unset = off):
    a hung filesystem dumps every thread's stack instead of wedging the
    run silently."""
    with _env_watchdog("MXTPU_CKPT_TIMEOUT", name):
        yield


# -- local checkpointer --------------------------------------------------------

_CKPT_MAGIC = b"MXTCKPT1"


#: version of the data-pipeline-state stamp wrapper (the inner state
#: dict carries its own ``gluon/data/state.py`` version independently)
_DATA_STATE_STAMP_VERSION = 1


def data_state_stamp(sd):
    """Wrap a data-pipeline ``state_dict`` (gluon/data/state.py) for the
    checkpoint path: versioned + CRC over the canonical JSON encoding.
    The stamp rides MANIFEST.json / peer-snapshot frames / the
    LocalCheckpointer sidecar as an OPTIONAL key — absent on runs that
    never attached a resumable loader, and old readers ignore it."""
    payload = json.dumps(sd, sort_keys=True, separators=(",", ":"))
    return {"version": _DATA_STATE_STAMP_VERSION,
            "crc": zlib.crc32(payload.encode()) & 0xffffffff,
            "state": sd}


def data_state_unstamp(stamp):
    """Validate + unwrap a :func:`data_state_stamp`.  Lenient on absence
    (None in, None out — pre-PR-19 checkpoints restore fine without a
    data position) but fail-closed on damage: a CRC/version mismatch
    raises CheckpointCorrupt rather than silently mis-aligning the
    sample stream."""
    if stamp is None:
        return None
    if not isinstance(stamp, dict) or "state" not in stamp:
        raise CheckpointCorrupt(
            f"data-pipeline state stamp malformed: {type(stamp).__name__}")
    if stamp.get("version") != _DATA_STATE_STAMP_VERSION:
        raise CheckpointCorrupt(
            f"data-pipeline state stamp version "
            f"{stamp.get('version')!r} (this build reads "
            f"{_DATA_STATE_STAMP_VERSION})")
    sd = stamp["state"]
    payload = json.dumps(sd, sort_keys=True, separators=(",", ":"))
    if zlib.crc32(payload.encode()) & 0xffffffff != stamp.get("crc"):
        raise CheckpointCorrupt(
            "data-pipeline state stamp: checksum mismatch")
    return sd


class LocalCheckpointer:
    """Single-host checkpoints with CRC-verified atomic writes.

    The same save/restore/latest_step/all_steps/wait surface as
    ``checkpoint.ShardedCheckpointer`` so :func:`run_resilient` composes
    with either; this one needs no orbax/jax and is what the hermetic
    fault tests (and single-host users) run.

    Format: ``MXTCKPT1 | crc32:u32 | length:u64 | pickle(state)`` written
    to a temp file and atomically renamed — a crash mid-write can never
    leave a half-written file under a valid name, and a corrupt/partial
    file fails closed via the checksum.
    """

    def __init__(self, directory, max_to_keep=3):
        self._dir = os.path.abspath(directory)
        os.makedirs(self._dir, exist_ok=True)
        self.max_to_keep = max_to_keep

    def _path(self, step):
        return os.path.join(self._dir, f"ckpt_{int(step):010d}.mxtckpt")

    def _data_path(self, step):
        return os.path.join(self._dir,
                            f"ckpt_{int(step):010d}.datastate.json")

    @staticmethod
    def _to_host(state):
        """Device arrays pickle as numpy (a restored checkpoint must not
        depend on the dying process's device layout)."""
        import numpy as np

        def conv(v):
            if isinstance(v, dict):
                return {k: conv(x) for k, x in v.items()}
            if isinstance(v, (list, tuple)):
                out = [conv(x) for x in v]
                return out if isinstance(v, list) else tuple(out)
            if hasattr(v, "__array__"):
                return np.asarray(v)
            return v

        return conv(state)

    def save(self, step, state, data_state=None):
        payload = pickle.dumps(self._to_host(state), protocol=4)
        header = _CKPT_MAGIC + struct.pack(
            "<IQ", zlib.crc32(payload) & 0xffffffff, len(payload))
        tmp = self._path(step) + ".tmp"
        with guard_checkpoint(f"ckpt_save:{step}"):
            if data_state is not None:
                # sidecar FIRST, so the .mxtckpt rename (the commit
                # point) never exposes a checkpoint whose data position
                # is still being written
                dtmp = self._data_path(step) + ".tmp"
                with open(dtmp, "w") as f:
                    json.dump(data_state_stamp(data_state), f)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(dtmp, self._data_path(step))
            with open(tmp, "wb") as f:
                f.write(header)
                f.write(payload)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self._path(step))
            # durability: the rename lives in the directory inode — fsync
            # it too, or power loss can roll the commit back
            fsync_dir(self._dir)
        if consume_charges("corrupt_ckpt_write", on_last=False):
            # bit-rot the file AFTER the commit rename: only the
            # verify-after-write readback (_save_verified) can catch it
            with open(self._path(step), "r+b") as f:
                f.seek(-1, os.SEEK_END)
                last = f.read(1)
                f.seek(-1, os.SEEK_END)
                f.write(bytes([last[0] ^ 0xFF]))
        self._prune()
        return step

    def _prune(self):
        steps = self.all_steps()
        for s in steps[:-self.max_to_keep] if self.max_to_keep else []:
            for path in (self._path(s), self._data_path(s)):
                try:
                    os.remove(path)
                except OSError:
                    pass

    def data_state(self, step=None):
        """The data-pipeline state saved alongside ``step`` (latest when
        None), or None when the checkpoint predates resumable loading —
        lenient on absence, fail-closed (CheckpointCorrupt) on a
        damaged stamp."""
        if step is None:
            step = self.latest_step()
            if step is None:
                return None
        try:
            with open(self._data_path(step)) as f:
                stamp = json.load(f)
        except FileNotFoundError:
            return None
        except ValueError as e:
            raise CheckpointCorrupt(
                f"{self._data_path(step)}: unparseable ({e})") from e
        return data_state_unstamp(stamp)

    def restore(self, step=None, template=None):
        if step is None:
            step = self.latest_step()
            if step is None:
                raise MXNetError(f"no checkpoints under {self._dir}")
        path = self._path(step)

        def read():
            with open(path, "rb") as f:
                return f.read()

        with guard_checkpoint(f"ckpt_restore:{step}"):
            blob = io_retry(read, description=f"read {path}")
        if len(blob) < len(_CKPT_MAGIC) + 12 or \
                not blob.startswith(_CKPT_MAGIC):
            raise CheckpointCorrupt(f"{path}: bad checkpoint magic")
        crc, length = struct.unpack(
            "<IQ", blob[len(_CKPT_MAGIC):len(_CKPT_MAGIC) + 12])
        payload = blob[len(_CKPT_MAGIC) + 12:]
        if len(payload) != length:
            raise CheckpointCorrupt(
                f"{path}: truncated (want {length} payload bytes, have "
                f"{len(payload)})")
        if zlib.crc32(payload) & 0xffffffff != crc:
            raise CheckpointCorrupt(f"{path}: checksum mismatch")
        return pickle.loads(payload)

    def verify(self, step):
        """Re-read and checksum a written checkpoint (verify-after-write).
        Raises CheckpointCorrupt on any mismatch."""
        self.restore(step)

    def all_steps(self):
        steps = []
        for name in os.listdir(self._dir):
            if name.startswith("ckpt_") and name.endswith(".mxtckpt"):
                try:
                    steps.append(int(name[5:-8]))
                except ValueError:
                    pass
        return sorted(steps)

    def latest_step(self):
        steps = self.all_steps()
        return steps[-1] if steps else None

    def wait(self):
        pass

    def close(self):
        pass


# -- resilient training driver -------------------------------------------------

class RunReport:
    """What :func:`run_resilient` did: where it resumed, how many
    restarts it burned, and the per-step loss trajectory."""

    def __init__(self):
        self.final_step = 0
        self.restarts = 0
        self.reshapes = 0        # elastic gang membership changes
        self.resumed_from = []   # checkpoint step of each (re)start
        self.losses = {}         # step -> float loss
        self.preempted = False

    def __repr__(self):
        return (f"RunReport(final_step={self.final_step}, "
                f"restarts={self.restarts}, reshapes={self.reshapes}, "
                f"resumed_from={self.resumed_from}, "
                f"preempted={self.preempted})")


def flush_inflight(checkpointer, logger=None):
    """Drain an async checkpointer's in-flight save at a recovery point.

    A failed background commit must not abort recovery — the previous
    checkpoint is still valid, which is the whole point of the two-phase
    commit — so errors are logged and swallowed here (they would have
    been raised from the next ``save()`` anyway).
    """
    wait = getattr(checkpointer, "wait", None)
    if wait is None:
        return
    pending = getattr(checkpointer, "pending_step", None)
    try:
        wait()
    except Exception as e:                      # noqa: BLE001
        _log(logger, f"in-flight checkpoint save failed ({e}); "
                     f"recovering from the previous checkpoint")
        _tel_event("inflight_save_dropped",
                   step=int(pending) if isinstance(pending, int) else None,
                   reason=type(e).__name__)


def resume_latest(checkpointer, set_state, logger=None):
    """Restore the newest VALID checkpoint; corrupt/partial ones fall
    back to the previous step.  Returns the restored step (0 = fresh).
    Any in-flight async save is drained first so a commit racing the
    restore can't be half-observed."""
    flush_inflight(checkpointer, logger)
    steps = sorted(checkpointer.all_steps(), reverse=True) \
        if hasattr(checkpointer, "all_steps") else \
        ([checkpointer.latest_step()]
         if checkpointer.latest_step() is not None else [])
    for step in steps:
        try:
            state = checkpointer.restore(step)
        except Exception as e:
            _log(logger, f"checkpoint step {step} unreadable ({e}); "
                         f"falling back to the previous one")
            _tel_event("ckpt_fallback", step=int(step),
                       reason=type(e).__name__)
            continue
        set_state(state)
        _log(logger, f"resumed from checkpoint step {step}")
        return step
    return 0


def _log(logger, msg):
    if logger is None:
        sys.stderr.write(f"[resilience] {msg}\n")
    else:
        logger.info(msg)


def _save_verified(checkpointer, step, state, logger=None,
                   data_state=None):
    """Save + verify-after-write; one rewrite attempt on a bad readback."""
    for attempt in range(2):
        if data_state is not None:
            checkpointer.save(step, state, data_state=data_state)
        else:
            checkpointer.save(step, state)
        checkpointer.wait()
        verify = getattr(checkpointer, "verify", None)
        if verify is None:
            return
        try:
            verify(step)
            return
        except CheckpointCorrupt as e:
            if attempt:
                raise
            _log(logger, f"checkpoint step {step} failed verification "
                         f"({e}); rewriting once")


def run_resilient(step_fn, checkpointer, num_steps, *, get_state,
                  set_state, checkpoint_every=None, max_restarts=3,
                  watchdog_timeout=None, exit_on_preempt=False,
                  recover_on=(RuntimeError, OSError), logger=None,
                  gang=None, on_reshape=None,
                  get_data_state=None, set_data_state=None):
    """Supervised training loop: auto-resume + preemption checkpointing +
    bounded in-process restarts.

    - ``step_fn(step) -> loss``: run ONE training step (0-based ``step``
      counts completed steps).  Must be a pure function of the current
      training state for crash-resume to reproduce the loss trajectory.
    - ``get_state() -> pytree`` / ``set_state(pytree)``: snapshot/load
      everything a restart needs (params, optimizer state, RNG, ...).
    - ``checkpointer``: LocalCheckpointer / checkpoint.AsyncCheckpointer /
      ShardedCheckpointer surface.  An async engine overlaps the
      serialize+fsync with training (its CRC-verified two-phase commit
      replaces the synchronous verify-after-write) and is drained at
      every recovery point and at the end of the run.
    - ``checkpoint_every``: steps between periodic saves; ``None`` reads
      ``MXTPU_CKPT_EVERY`` (default 25), ``0`` disables.
    - On SIGTERM (TPU preemption notice) the current state is
      checkpointed; with ``exit_on_preempt`` the driver returns (the
      process is about to die), otherwise the preemption is treated as
      an in-process restart and counted against ``max_restarts`` — the
      hermetic analog of kill-and-relaunch.
    - A step failure in ``recover_on`` (or a watchdog expiry) restores
      the latest valid checkpoint and replays; corrupt checkpoints fall
      back to the previous step.
    - ``gang`` (an :class:`ElasticGang`): gang-level recovery.  Each
      step ticks the health plane (heartbeat step ids, peer snapshots,
      failure-detector poll); a confirmed peer death raises
      :class:`RankFailure`, which runs ``gang.recover`` — survivors
      agree a new epoch and keep training — instead of the full-restart
      path.  ``on_reshape(info)`` merges the recovered per-rank shards
      back into trainer state and returns the resume step (or a
      ``(step, new_checkpointer)`` tuple when the reshape rebuilds the
      checkpoint engine for the new world size); without the callback
      only disk-sourced recoveries (``info.full_state``) can be applied.
    - ``get_data_state() -> dict`` / ``set_data_state(dict)``: the input
      pipeline's position (``DataLoader.state_dict`` /
      ``load_state_dict``, gluon/data/state.py).  Saved alongside every
      checkpoint (MANIFEST.json stamp or LocalCheckpointer sidecar) and
      re-adopted leniently at every resume point — including gang
      reshapes — so the sample stream rewinds in lockstep with the
      trainer state: zero re-read, zero skipped samples.

    Returns a :class:`RunReport`.
    """
    from .checkpoint import PreemptionHandler

    if checkpoint_every is None:
        checkpoint_every = int(os.environ.get("MXTPU_CKPT_EVERY", 25))
    # async engines own crash consistency via the two-phase commit; the
    # synchronous readback verify would serialize the save we just made
    # asynchronous
    is_async = bool(getattr(checkpointer, "async_save", False))

    def save_at(step):
        ds = None
        if get_data_state is not None and \
                hasattr(checkpointer, "data_state"):
            ds = get_data_state()
        if is_async:
            if ds is not None:
                checkpointer.save(step, get_state(), data_state=ds)
            else:
                checkpointer.save(step, get_state())
        else:
            _save_verified(checkpointer, step, get_state(), logger,
                           data_state=ds)

    def adopt_data_state(step):
        """Rewind the input pipeline to the restored step's position —
        lenient when the checkpoint carries none (pre-data-state
        manifests, fresh starts)."""
        if set_data_state is None or not step:
            return
        ds_fn = getattr(checkpointer, "data_state", None)
        ds = ds_fn(step) if ds_fn is not None else None
        if ds is not None:
            set_data_state(ds)

    report = RunReport()
    step = resume_latest(checkpointer, set_state, logger)
    adopt_data_state(step)
    report.resumed_from.append(step)
    _tel_event("resume", step=step)
    last_saved = step
    step_box = [step]

    def gang_reshape(rf):
        """Shared RankFailure handler (step tick, step fn, or a gang-
        coordinated checkpoint barrier may raise it)."""
        nonlocal step, checkpointer, is_async, last_saved
        info = gang.recover(rf, checkpointer=checkpointer)
        report.reshapes += 1
        if on_reshape is not None:
            res = on_reshape(info)
            if isinstance(res, tuple):
                step, checkpointer = res
            else:
                step = int(res) if res is not None else info.snap_step
        elif info.full_state is not None:
            set_state(info.full_state)
            step = info.snap_step
        else:
            raise MXNetError(
                "run_resilient: gang recovery assembled per-rank peer "
                "shards; pass on_reshape= to merge them into trainer "
                "state") from rf
        is_async = bool(getattr(checkpointer, "async_save", False))
        adopt_data_state(step)
        last_saved = step
        step_box[0] = step
        report.resumed_from.append(step)
        _log(logger, f"gang reshaped to epoch {info.epoch} (world "
                     f"{info.world}); resuming at step {step}")

    with PreemptionHandler(checkpointer, get_state,
                           lambda: step_box[0]) as handler:
        while step < num_steps:
            step_box[0] = step
            # fault injection: deliver a real SIGTERM to ourselves at
            # step S — exercises the whole preemption path
            if fault_arg("sigterm_at_step") == step and \
                    consume_fault("sigterm_at_step"):
                os.kill(os.getpid(), signal.SIGTERM)
            if handler.preempted.is_set():
                handler.maybe_checkpoint()   # saves at current step
                last_saved = step
                report.preempted = True
                if exit_on_preempt:
                    report.final_step = step
                    return report
                if report.restarts >= max_restarts:
                    raise MXNetError(
                        f"run_resilient: preempted with no restarts left "
                        f"(max_restarts={max_restarts})")
                report.restarts += 1
                handler.preempted.clear()
                step = resume_latest(checkpointer, set_state, logger)
                adopt_data_state(step)
                report.resumed_from.append(step)
                _tel_event("restart", step=step, reason="preempted")
                continue
            try:
                if gang is not None:
                    gang.step_tick(step, state_fn=get_state)
                if watchdog_timeout:
                    with Watchdog(watchdog_timeout,
                                  name=f"step {step}"):
                        loss = step_fn(step)
                else:
                    loss = step_fn(step)
            except RankFailure as rf:
                if gang is None:
                    raise
                gang_reshape(rf)
                continue
            except recover_on as e:
                if report.restarts >= max_restarts:
                    raise
                report.restarts += 1
                _log(logger, f"step {step} failed ({type(e).__name__}: "
                             f"{e}); restart "
                             f"{report.restarts}/{max_restarts}")
                reason = type(e).__name__
                step = resume_latest(checkpointer, set_state, logger)
                adopt_data_state(step)
                report.resumed_from.append(step)
                _tel_event("restart", step=step, reason=reason)
                continue
            if loss is not None:
                try:
                    report.losses[step] = float(loss)
                except (TypeError, ValueError):
                    pass
            step += 1
            if checkpoint_every and step % checkpoint_every == 0:
                try:
                    save_at(step)
                except RankFailure as rf:
                    if gang is None:
                        raise
                    gang_reshape(rf)   # a peer died inside the gang-
                    continue           # coordinated commit barrier
                last_saved = step
        if step > last_saved:
            save_at(step)
        if is_async:
            checkpointer.wait()   # the final commit must land before we
    report.final_step = step      # report the run finished
    return report


# -- elastic gang recovery (health plane + membership protocol) ----------------

class RankFailure(MXNetError):
    """A gang membership change is required: peers confirmed dead and/or
    respawned ranks asking to rejoin.  Raised by `ElasticGang.step_tick`
    (and gang barriers); the handler calls `ElasticGang.recover`."""

    def __init__(self, dead, epoch, joiners=(), planned=False,
                 at_step=None):
        self.dead = sorted(dead)
        self.joiners = sorted(joiners)
        self.epoch = int(epoch)
        self.at_step = at_step         # planned reshape's agreed step
        self.planned = bool(planned)   # scheduled drain/admit, nobody
        what = []                      # actually died — no detection
        if self.dead:                  # window, zero lost steps
            what.append(f"{'leaving' if planned else 'dead'} ranks "
                        f"{self.dead}")
        if self.joiners:
            what.append(f"join requests {self.joiners}")
        super().__init__(
            f"gang membership change at epoch {epoch}"
            f"{' (planned)' if planned else ''}: "
            f"{', '.join(what) or 'unknown'}")


class GangEvicted(MXNetError):
    """The agreed epoch excludes THIS rank — the survivors declared it
    dead (a wedge that later unwedged, a partition, a false positive).
    The only safe move is a clean exit: rejoining with stale state would
    corrupt the reshaped gang.  Workers treat this as exit code 0."""


class GangFenced(MXNetError):
    """This rank is on the WRONG side of a partition (or cannot reach a
    quorum of the previous epoch's members): it must not step, must not
    commit anything durable, and must not propose an epoch.  Unlike
    `GangEvicted` this is recoverable — the rank keeps heartbeating,
    parks in `ElasticGang.park_fenced`, and rejoins via `join_req` when
    the partition heals, adopting the majority's state instead of its
    own.  Raised by `step_tick`/`recover` when the KV is unreachable or
    a reshape deadline passes without a strict majority of the previous
    epoch acking."""

    def __init__(self, reason, epoch=None):
        self.reason = str(reason)
        self.epoch = epoch
        super().__init__(
            f"gang fenced at epoch {epoch}: {reason}" if epoch is not None
            else f"gang fenced: {reason}")


class HeartbeatPublisher:
    """Per-rank liveness beacon: a daemon thread publishes
    ``hb/<rank> = {rank, seq, step, t}`` to the gang KV every
    ``MXTPU_HEARTBEAT_INTERVAL`` (0.5s).  ``seq`` is what the failure
    detector watches — strictly monotonic per publish, so a stalled
    clock or republished file can't fake liveness.  ``note_step`` keeps
    the payload's step id fresh (the straggler monitor's lag signal).

    The ``heartbeat_loss:K`` fault site suppresses publishing while the
    process keeps running: the wedged-but-alive failure mode, which must
    look exactly like death to the detector.
    """

    def __init__(self, kv, rank, interval=None):
        self.kv = kv
        self.rank = int(rank)
        self.interval = float(
            os.environ.get("MXTPU_HEARTBEAT_INTERVAL", 0.5)
            if interval is None else interval)
        self._step = 0
        self._seq = 0
        self._stop = threading.Event()
        self._thread = None

    def note_step(self, step):
        self._step = int(step)

    def publish_once(self):
        if self.rank in fault_args("heartbeat_loss"):
            return
        self._seq += 1
        self.kv.put_json(f"hb/{self.rank}",
                         {"rank": self.rank, "seq": self._seq,
                          "step": self._step, "t": time.time()})

    def _loop(self):
        while not self._stop.is_set():
            try:
                self.publish_once()
            except Exception:       # noqa: BLE001 — liveness reporting
                pass                # must never kill training
            self._stop.wait(self.interval)

    def start(self):
        if self._thread is None:
            self.publish_once()     # visible before the first interval
            self._thread = threading.Thread(
                target=self._loop, name=f"heartbeat:{self.rank}",
                daemon=True)
            self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None


class _PeerHealth:
    __slots__ = ("seq", "step", "last_change", "arrivals", "suspected")

    def __init__(self, now):
        self.seq = None
        self.step = None
        self.last_change = now
        self.arrivals = collections.deque(maxlen=32)
        self.suspected = False


class FailureDetector:
    """Phi-style accrual failure detector over KV heartbeats.

    Suspicion is *accrual*: phi = silence / mean-observed-interarrival,
    so a peer that heartbeats every 0.1s is suspected after ~1s of
    silence while a peer on a slow NFS gang dir isn't — the threshold
    adapts to each peer's own cadence (``MXTPU_PHI_SUSPECT``, 8.0).
    Suspicion only emits a ``rank_suspected`` telemetry event (once per
    silence episode); *death* is confirmed by the hard wall-clock
    timeout ``MXTPU_HEARTBEAT_TIMEOUT`` (5s), which is what the reshape
    protocol acts on — a deliberately conservative two-level scheme so
    one GC pause can't trigger a reshard.

    ``poll()`` is throttled to ``check_interval`` (half the heartbeat
    interval), so calling it every training step costs a dict lookup,
    not a KV scan.
    """

    def __init__(self, kv, rank, peers, *, timeout=None,
                 suspect_phi=None, check_interval=None):
        self.kv = kv
        self.rank = int(rank)
        self.timeout = float(
            os.environ.get("MXTPU_HEARTBEAT_TIMEOUT", 5.0)
            if timeout is None else timeout)
        self.suspect_phi = float(
            os.environ.get("MXTPU_PHI_SUSPECT", 8.0)
            if suspect_phi is None else suspect_phi)
        if check_interval is None:
            check_interval = float(
                os.environ.get("MXTPU_HEARTBEAT_INTERVAL", 0.5)) / 2.0
        self.check_interval = max(1e-3, float(check_interval))
        self._peers = {}
        now = time.monotonic()
        for p in peers:
            if int(p) != self.rank:
                self._peers[int(p)] = _PeerHealth(now)
        self._last_check = 0.0
        self._dead = set()

    def watch(self, rank):
        if int(rank) != self.rank and int(rank) not in self._peers:
            self._peers[int(rank)] = _PeerHealth(time.monotonic())
        self._dead.discard(int(rank))

    def forget(self, rank):
        self._peers.pop(int(rank), None)
        self._dead.discard(int(rank))

    def peer_steps(self):
        """Last heartbeat-published step id per watched peer (None until
        the first heartbeat lands)."""
        return {p: h.step for p, h in self._peers.items()}

    def poll(self, force=False):
        """Returns the set of CONFIRMED-dead peers (silence beyond the
        hard timeout).  Throttled; pass force=True to re-read the KV
        regardless (recovery paths)."""
        now = time.monotonic()
        if not force and now - self._last_check < self.check_interval:
            return set(self._dead)
        self._last_check = now
        for p, h in self._peers.items():
            rec = self.kv.get_json(f"hb/{p}")
            seq = rec.get("seq") if isinstance(rec, dict) else None
            if seq is not None and seq != h.seq:
                if h.seq is not None:
                    h.arrivals.append(now - h.last_change)
                h.seq = seq
                h.step = rec.get("step")
                h.last_change = now
                h.suspected = False
                self._dead.discard(p)
                continue
            silence = now - h.last_change
            mean = (sum(h.arrivals) / len(h.arrivals)) \
                if h.arrivals else None
            phi = silence / mean if mean else 0.0
            if not h.suspected and (phi >= self.suspect_phi
                                    or silence >= self.timeout / 2.0):
                h.suspected = True
                _tel_event("rank_suspected", rank=p,
                           silence_s=round(silence, 3),
                           phi=round(phi, 2))
            if silence >= self.timeout:
                self._dead.add(p)
        return set(self._dead)


class StragglerMonitor:
    """Names the slow rank behind persistent collective waits.

    Fed the per-step collective-wait share (telemetry StepStats
    ``shares["collective"]``): when the mean share over the last
    ``MXTPU_STRAGGLER_WINDOW`` (20) steps exceeds
    ``MXTPU_STRAGGLER_SHARE`` (0.5), this rank is mostly waiting for a
    peer — and the peer whose heartbeat-published step id is furthest
    behind is the one everyone is waiting on.  Emits a
    ``straggler_suspected`` event (at most once per window) naming it;
    detection only — eviction stays a human/provisioner decision, since
    a straggler still makes progress.
    """

    def __init__(self, detector, *, window=None, share_threshold=None):
        self.detector = detector
        self.window = int(os.environ.get("MXTPU_STRAGGLER_WINDOW", 20)
                          if window is None else window)
        self.share_threshold = float(
            os.environ.get("MXTPU_STRAGGLER_SHARE", 0.5)
            if share_threshold is None else share_threshold)
        self._shares = collections.deque(maxlen=max(1, self.window))
        self._last_emit_step = None

    def observe(self, step, collective_share):
        """Returns the suspected rank when one is (newly) named."""
        if collective_share is None:
            return None
        self._shares.append(float(collective_share))
        if len(self._shares) < self.window:
            return None
        mean = sum(self._shares) / len(self._shares)
        if mean < self.share_threshold:
            return None
        if self._last_emit_step is not None and \
                step - self._last_emit_step < self.window:
            return None
        steps = {p: s for p, s in self.detector.peer_steps().items()
                 if s is not None and s <= step}
        if not steps:
            return None
        laggard = min(steps, key=steps.get)
        self._last_emit_step = step
        _tel_event("straggler_suspected", rank=laggard, step=int(step),
                   mean_collective_share=round(mean, 3),
                   laggard_step=int(steps[laggard]))
        return laggard


class RecoveryInfo:
    """What `ElasticGang.recover` agreed and assembled."""

    def __init__(self, *, epoch, members, snap_step, source, dead,
                 joined, recovery_ms, shards=None, full_state=None,
                 old_members=(), planned=False):
        self.epoch = int(epoch)
        self.members = list(members)
        self.snap_step = int(snap_step)
        self.source = source            # "peer" | "disk"
        self.dead = sorted(dead)
        self.joined = sorted(joined)
        self.recovery_ms = float(recovery_ms)
        self.shards = shards            # {old_rank: shard state} (peer)
        self.full_state = full_state    # full pytree (disk)
        self.old_members = list(old_members)
        self.planned = bool(planned)    # drain/admit, not a death

    @property
    def world(self):
        return len(self.members)

    def __repr__(self):
        return (f"RecoveryInfo(epoch={self.epoch}, "
                f"members={self.members}, snap_step={self.snap_step}, "
                f"source={self.source!r}, dead={self.dead}, "
                f"joined={self.joined}, "
                f"recovery_ms={self.recovery_ms:.1f})")


class ElasticGang:
    """The elastic membership runtime one rank participates in.

    Composes the health plane (heartbeats out, failure detection in,
    straggler naming) with peer-replicated RAM snapshots
    (`checkpoint.PeerSnapshotStore`) and the epoch-consensus reshape
    protocol.  The control plane is `distributed.gang_kv()` — a shared
    directory (``MXTPU_GANG_DIR``) or the coordination-service KV —
    chosen for exactly one property the collective plane lacks: it
    keeps working while a member is dead.

    Protocol sketch (docs/resilience.md has the full diagram)::

        steady state   every rank:  hb/<r> <- {seq, step}        (0.5 s)
                       every PEER_SNAP_EVERY steps:
                           own shard -> buddy's RAM  (+ hold own)
                           snap/<r> <- {step, epoch}
        death          detector: silence(hb/<k>) > TIMEOUT
                       survivors raise RankFailure -> recover():
                         min(survivors) proposes epoch/current <-
                           {epoch+1, members, dead, snap_step, source}
                         all new members ack epoch_ack/<e>/<r>
                         shards assembled: own RAM + live peers' RAM +
                           dead ranks' shards from their buddies' RAM;
                           disk manifest (PR 5) only when a buddy died
                       training resumes at snap_step, epoch e+1
        rejoin         respawned rank: join_req/<r>; proposer admits at
                       the next epoch; everyone rolls back to the agreed
                       snapshot, joiner fetches all shards from peers

    ``step_tick`` raises :class:`RankFailure` (membership change needed)
    or :class:`GangEvicted` (this rank was declared dead); the caller —
    `run_resilient(gang=...)` or a bespoke train loop — runs
    ``recover`` and continues from the returned :class:`RecoveryInfo`.
    """

    def __init__(self, rank, world, *, kv=None, peers=None,
                 heartbeat_interval=None, heartbeat_timeout=None,
                 peer_snap_every=None, reshape_timeout=None,
                 checkpointer=None):
        if kv is None:
            from . import distributed

            kv = distributed.gang_kv()
        if kv is None:
            raise MXNetError(
                "ElasticGang needs a control plane: set MXTPU_GANG_DIR "
                "to a shared directory (or run under a coordination "
                "service)")
        self.kv = kv
        self.rank = int(rank)
        self.members = list(range(int(world)))
        self.epoch = 0
        _tel_identity(rank=self.rank, world=len(self.members))
        self.checkpointer = checkpointer
        # quorum-gated reshape (split-brain safety): an epoch commit
        # needs acks from a STRICT majority of the previous epoch's
        # members — dead ranks count against, not for.  MXTPU_QUORUM=0
        # is the force-new-cluster escape hatch for deliberate
        # minority-survivor restarts (e.g. 3->1 disk fallback).
        self._quorum = os.environ.get("MXTPU_QUORUM", "1").lower() \
            not in ("0", "false", "")
        self._fenced_at = None
        if self.checkpointer is not None:
            attach = getattr(self.checkpointer, "attach_gang", None)
            if attach is not None:
                attach(lambda: self.epoch, self._committed_epoch)
        self.peer_snap_every = int(
            os.environ.get("MXTPU_PEER_SNAP_EVERY", 10)
            if peer_snap_every is None else peer_snap_every)
        self.reshape_timeout = float(
            os.environ.get("MXTPU_RESHAPE_TIMEOUT", 60.0)
            if reshape_timeout is None else reshape_timeout)
        # steps of notice a planned reshape (drain/admit) gives the
        # gang: every member must tick the agreed step AFTER the plan
        # lands, so it must exceed the worst lockstep skew (1 step)
        self.drain_margin = max(
            2, int(os.environ.get("MXTPU_SCALE_MARGIN", 2)))
        self.hb = HeartbeatPublisher(kv, rank,
                                     interval=heartbeat_interval)
        self.detector = FailureDetector(kv, rank, self.members,
                                        timeout=heartbeat_timeout)
        self.straggler = StragglerMonitor(self.detector)
        if peers is None:
            from .checkpoint import PeerSnapshotStore

            peers = PeerSnapshotStore(rank, kv=kv)
        self.peers = peers
        self._last_snap_step = None
        self._started = False

    # -- membership helpers ----------------------------------------------------

    def buddy_of(self, rank, members=None):
        """The next member ring-wise — who holds ``rank``'s RAM shard."""
        m = members if members is not None else self.members
        i = m.index(rank)
        return m[(i + 1) % len(m)]

    def _is_proposer(self, survivors=None):
        alive = survivors if survivors is not None else self.members
        return alive and self.rank == min(alive)

    # -- fencing helpers -------------------------------------------------------

    def _committed_epoch(self):
        """Highest committed epoch: the KV's fence when it keeps one,
        else the ``epoch/current`` record.  Raises when the KV is
        unreachable (a partitioned caller must treat that as stale)."""
        ce = getattr(self.kv, "committed_epoch", None)
        if ce is not None:
            return int(ce())
        cur = self.kv.get_json("epoch/current")
        return int(cur.get("epoch", 0)) if cur else 0

    def _fence_to(self, epoch):
        """Propagate the adopted epoch to every durable-write plane:
        telemetry step records (schema v8 ``gang_epoch``) and the peer
        snapshot receiver's frame fence."""
        _tel_set_epoch(epoch)
        fence = getattr(self.peers, "fence", None)
        if fence is not None:
            try:
                fence(int(epoch))
            except Exception:       # noqa: BLE001 — best-effort
                pass

    def _fenced(self, reason):
        """Build (and announce) the fenced state: the caller raises the
        returned :class:`GangFenced` and parks in `park_fenced`."""
        if self._fenced_at is None:
            self._fenced_at = time.monotonic()
        _tel_event("gang_fenced", rank=self.rank, epoch=self.epoch,
                   reason=str(reason)[:200])
        sys.stderr.write(
            f"[resilience] rank {self.rank}: FENCED at epoch "
            f"{self.epoch}: {reason}\n")
        return GangFenced(reason, epoch=self.epoch)

    def _put_json_fenced(self, key, obj, epoch):
        """Fenced compare-and-swap write when the KV supports it
        (`put_json_if_epoch`), plain put otherwise (CoordKV)."""
        put = getattr(self.kv, "put_json_if_epoch", None)
        if put is None:
            self.kv.put_json(key, obj)
        else:
            put(key, obj, int(epoch))

    # -- lifecycle -------------------------------------------------------------

    def start(self):
        if self._started:
            return self
        self.peers.start()
        cur = self.kv.get_json("epoch/current")
        if cur is None and self._is_proposer():
            self.kv.put_json("epoch/current",
                             {"epoch": 0, "members": self.members,
                              "dead": [], "joined": [],
                              "proposer": self.rank, "t": time.time()})
        elif cur is not None and int(cur.get("epoch", 0)) >= self.epoch \
                and self.rank in cur.get("members", []):
            self.epoch = int(cur["epoch"])
            self.members = list(cur["members"])
            _tel_identity(rank=self.rank, world=len(self.members))
            self.detector = FailureDetector(
                self.kv, self.rank, self.members,
                timeout=self.detector.timeout)
            self.straggler.detector = self.detector
        self._fence_to(self.epoch)
        self.hb.start()
        self._started = True
        return self

    def stop(self):
        self.hb.stop()
        self.peers.close()
        self._started = False

    # -- per-step health tick --------------------------------------------------

    def step_tick(self, step, state=None, state_fn=None,
                  collective_share=None):
        """Call once per training step (cheap: throttled KV reads).

        Publishes the step id, takes the periodic peer snapshot (from
        ``state`` or lazily from ``state_fn()``), feeds the straggler
        monitor, and raises :class:`RankFailure` on a confirmed peer
        death / pending join, :class:`GangEvicted` when a newer epoch
        excludes this rank, or :class:`GangFenced` when the gang KV is
        unreachable (this rank is on the losing side of a partition —
        park in :meth:`park_fenced`).
        """
        maybe_slow_rank(self.rank)
        maybe_kill_rank(self.rank, step)
        maybe_pause_rank(self.rank)
        self.hb.note_step(step)
        try:
            # zombie containment: learn the committed epoch FIRST — a
            # rank resumed after a suspension (SIGSTOP, preemptor
            # pause) must discover its eviction BEFORE the snapshot's
            # durable writes below, not after
            self._check_epoch()
            if self.peer_snap_every and step % self.peer_snap_every == 0 \
                    and step != self._last_snap_step:
                if state is None and state_fn is not None:
                    state = state_fn()
                if state is not None:
                    self.snapshot(step, state)
            self.straggler.observe(step, collective_share)
            plan = self._pending_reshape(step)
            if plan is not None:
                # planned reshape due NOW: snapshot at this exact step
                # so the whole gang shares the restore point (zero lost
                # steps), then reshape with no detection window
                leavers, admits, at_step = plan
                if state is None and state_fn is not None:
                    state = state_fn()
                if state is not None and self._last_snap_step != step:
                    self.snapshot(step, state)
                raise RankFailure(leavers, self.epoch, joiners=admits,
                                  planned=True, at_step=at_step)
            dead = self.detector.poll() & set(self.members)
            dead.discard(self.rank)
            if dead:
                raise RankFailure(dead, self.epoch)
            if self._is_proposer():
                joiners = self._pending_joiners()
                if joiners:
                    self._schedule_admit(step, joiners)
        except _gang_kv_errors() as e:
            raise self._fenced(e) from e

    def snapshot(self, step, state):
        """RAM-replicate this rank's shard of ``state``: hold our own
        copy and ship one to the buddy; advertise the step in the KV so
        a future proposal can pick a common restore point."""
        self._last_snap_step = step
        self.peers.hold_own(step, state, epoch=self.epoch)
        buddy = self.buddy_of(self.rank)
        if buddy != self.rank:
            self.peers.send_to(buddy, step, state, epoch=self.epoch)
        from . import distributed
        try:
            self._put_json_fenced(
                f"snap/{self.rank}",
                {"step": int(step),
                 "steps": self.peers.held_steps(self.rank,
                                                epoch=self.epoch),
                 "epoch": self.epoch},
                self.epoch)
        except distributed.FencedWrite:
            # a newer epoch committed while this rank was out to lunch
            # — it is a zombie.  _check_epoch tells the real story
            # (evicted vs still-member-of-newer-epoch); if the record
            # is somehow unreadable, evict conservatively.
            self._check_epoch()
            raise GangEvicted(
                f"rank {self.rank}: snapshot write fenced at epoch "
                f"{self.epoch} (a newer epoch committed while this "
                f"rank was suspended); exiting cleanly")
        # departed ranks' shards are freed HERE, not in recover():
        # forgetting there races a slower survivor's fetch of the
        # departed rank's shard from this rank's RAM.  Prune only once
        # every current member has signalled end-of-assembly
        # (epoch_done/<e>/<r>, written at the bottom of recover)
        prune = getattr(self.peers, "prune_ranks", None)
        held_ranks = getattr(self.peers, "held_ranks", None)
        if prune is not None and held_ranks is not None and \
                any(r not in self.members for r in held_ranks()):
            done = set()
            for key, _ in self.kv.scan(f"epoch_done/{self.epoch}"):
                try:
                    done.add(int(key.rsplit("/", 1)[1]))
                except ValueError:
                    pass
            if set(self.members) <= done:
                prune(self.members)

    def _check_epoch(self):
        cur = self.kv.get_json("epoch/current")
        if cur and int(cur.get("epoch", 0)) > self.epoch:
            if self.rank not in cur.get("members", []):
                raise GangEvicted(
                    f"rank {self.rank}: epoch {cur['epoch']} members "
                    f"{cur.get('members')} exclude this rank (declared "
                    f"dead); exiting cleanly")
            raise RankFailure(cur.get("dead", []), self.epoch,
                              joiners=cur.get("joined", []))
        # an epoch still in its ack round (epoch/proposed, uncommitted):
        # members named by it must enter recover() and ack — the quorum
        # gate needs their votes.  A rank the proposal EXCLUDES keeps
        # ticking: its writes carry the old epoch, which stays valid
        # until the commit advances the fence, and an uncommitted
        # proposal (it may never reach quorum) must not evict anyone.
        prop = self.kv.get_json("epoch/proposed")
        if prop and int(prop.get("epoch", 0)) > self.epoch \
                and self.rank in prop.get("members", []):
            raise RankFailure(prop.get("dead", []), self.epoch,
                              joiners=prop.get("joined", []))

    def _pending_joiners(self):
        joiners = []
        for key, _ in self.kv.scan("join_req"):
            rec = self.kv.get_json(key)
            r = rec.get("rank") if isinstance(rec, dict) else None
            if r is not None and r not in self.members:
                joiners.append(int(r))
        return sorted(set(joiners))

    # -- planned reshape (drain / scheduled admit) -----------------------------

    def plan_leave(self, at_step):
        """Schedule this rank's planned departure at ``at_step`` (a
        preemption drain).  Every member — including this rank — keeps
        stepping normally until its own tick of ``at_step``, snapshots
        there, and reshapes; the leaver is excluded from the new epoch
        and exits via :class:`GangEvicted`.  No detection window, no
        lost steps.  ``at_step`` must be at least ``drain_margin``
        steps ahead."""
        at = int(at_step)
        self.kv.put_json(f"leave/{self.rank}",
                         {"rank": self.rank, "at_step": at,
                          "epoch": self.epoch, "t": time.time()})
        _tel_event("gang_drain_scheduled", rank=self.rank, at_step=at,
                   epoch=self.epoch)
        return at

    def _schedule_admit(self, step, joiners):
        """Proposer only: schedule joiners for a planned admit a few
        steps out instead of reshaping immediately — every member then
        snapshots at the same agreed step, so admission loses no
        steps."""
        admit = self.kv.get_json("admit/plan")
        if isinstance(admit, dict) and \
                int(admit.get("epoch", -1)) == self.epoch:
            return      # one pending admit at a time; next epoch
        self.kv.put_json("admit/plan",
                         {"epoch": self.epoch,
                          "at_step": int(step) + self.drain_margin,
                          "joiners": sorted(joiners),
                          "t": time.time()})

    def _pending_reshape(self, step):
        """The planned membership change due at this tick, as
        ``(leavers, joiners, at_step)`` — or None when nothing is due
        yet.  Scheduled leaves and a scheduled admit that fall due
        together reshape in one epoch."""
        leavers, due_at = [], []
        for key, _ in self.kv.scan("leave"):
            rec = self.kv.get_json(key)
            if not isinstance(rec, dict):
                continue
            r = rec.get("rank")
            if r is None or int(r) not in self.members:
                continue
            at = int(rec.get("at_step", step))
            if at <= step:
                leavers.append(int(r))
                due_at.append(at)
        joiners = []
        admit = self.kv.get_json("admit/plan")
        if isinstance(admit, dict) and \
                int(admit.get("epoch", -1)) == self.epoch and \
                int(admit.get("at_step", step)) <= step:
            joiners = [int(j) for j in admit.get("joiners", ())
                       if int(j) not in self.members]
            if joiners:
                due_at.append(int(admit.get("at_step", step)))
        if not leavers and not joiners:
            return None
        return sorted(set(leavers)), joiners, max(due_at)

    # -- gang barrier ----------------------------------------------------------

    def barrier(self, name, timeout=None):
        """KV-plane barrier that stays responsive to member death: a
        dead peer raises :class:`RankFailure` instead of hanging (unlike
        the coordination-service barrier, which fate-shares)."""
        self.kv.put_json(f"barrier/{self.epoch}/{name}/{self.rank}",
                         {"rank": self.rank, "t": time.time()})
        deadline = time.monotonic() + (timeout or self.reshape_timeout)
        want = set(self.members)
        while True:
            present = set()
            for key, _ in self.kv.scan(f"barrier/{self.epoch}/{name}"):
                try:
                    present.add(int(key.rsplit("/", 1)[1]))
                except ValueError:
                    pass
            if want <= present:
                return
            self._check_epoch()
            dead = self.detector.poll() & want
            dead.discard(self.rank)
            if dead:
                raise RankFailure(dead, self.epoch)
            if time.monotonic() > deadline:
                raise MXNetError(
                    f"gang barrier {name!r} (epoch {self.epoch}): "
                    f"missing ranks {sorted(want - present)} after "
                    f"{timeout or self.reshape_timeout}s")
            time.sleep(0.01)

    # -- reshape protocol ------------------------------------------------------

    def recover(self, failure=None, checkpointer=None):
        """Run the epoch-consensus reshape and assemble the restore
        state.  Returns a :class:`RecoveryInfo`; the caller re-partitions
        its trainer state from ``info.shards`` (peer source) or
        ``info.full_state`` (disk source) and resumes at
        ``info.snap_step``.  Raises :class:`GangFenced` when the KV
        becomes unreachable mid-reshape or the proposal cannot gather a
        strict majority of the previous epoch's acks."""
        try:
            return self._recover_inner(failure, checkpointer)
        except _gang_kv_errors() as e:
            raise self._fenced(e) from e

    def _recover_inner(self, failure=None, checkpointer=None):
        t0 = time.monotonic()
        ck = checkpointer or self.checkpointer
        dead = set(failure.dead) if failure is not None else set()
        joiners = set(failure.joiners) if failure is not None else set()
        planned = bool(getattr(failure, "planned", False))
        target = getattr(failure, "at_step", None)
        old_members = list(self.members)
        proposal = self._await_proposal(dead, joiners, ck,
                                        target_step=target,
                                        planned=planned)
        epoch = int(proposal["epoch"])
        new_members = [int(r) for r in proposal["members"]]
        if self.rank not in new_members:
            raise GangEvicted(
                f"rank {self.rank}: reshape to epoch {epoch} excludes "
                f"this rank; exiting cleanly")
        old_members = [int(r) for r in
                       proposal.get("old_members", old_members)]
        dead = set(int(r) for r in proposal.get("dead", []))
        joined = [int(r) for r in proposal.get("joined", [])]
        self.kv.put_json(f"epoch_ack/{epoch}/{self.rank}",
                         {"rank": self.rank, "t": time.time()})
        self._await_acks(epoch, new_members, old_members, proposal)
        cur = self.kv.get_json("epoch/current") or {}
        if int(cur.get("epoch", -1)) == epoch and \
                sorted(int(r) for r in cur.get("members", [])) \
                != sorted(new_members):
            # amended in place: a proposed member died before acking
            new_members = [int(r) for r in cur["members"]]
            dead = set(int(r) for r in cur.get("dead", []))
            joined = [int(r) for r in cur.get("joined", [])]
            if self.rank not in new_members:
                raise GangEvicted(
                    f"rank {self.rank}: epoch {epoch} was amended to "
                    f"exclude this rank; exiting cleanly")
        source = proposal.get("source", "disk")
        snap_step = int(proposal["snap_step"])
        shards = None
        full_state = None
        if source == "peer":
            shards = self._assemble_shards(snap_step, old_members, dead)
            if shards is None:
                source = "disk"     # a holder vanished under us
        if source == "disk":
            if ck is None:
                raise MXNetError(
                    "elastic recovery needs the disk manifest (no RAM "
                    "coverage) but no checkpointer is attached")
            disk_step = proposal.get("disk_step")
            snap_step = int(disk_step if disk_step is not None
                            else ck.latest_step())
            full_state = ck.restore(snap_step)
            _tel_count("elastic.disk_restores")
        planned = bool(proposal.get("planned", planned))
        # adopt the new membership
        self.epoch = epoch
        self.members = new_members
        self._fenced_at = None
        self._fence_to(epoch)
        _tel_identity(rank=self.rank, world=len(self.members))
        for d in dead:
            self.detector.forget(d)
        for j in joined:
            self.detector.watch(j)
        self._last_snap_step = None
        # invalidate cached collective/captured programs — but only when
        # the kvstore module is actually loaded (importing it would pull
        # jax into a jax-free hermetic gang, and with no module loaded
        # there are no cached programs to invalidate)
        _kvstore = sys.modules.get((__package__ or "mxnet_tpu")
                                   + ".kvstore")
        if _kvstore is not None:
            try:
                _kvstore.notify_mesh_reshape(epoch)
            except Exception:       # noqa: BLE001 — best-effort
                pass
        ms = (time.monotonic() - t0) * 1000.0
        for d in sorted(dead):
            if planned:
                _tel_event("rank_drained", rank=d, epoch=epoch)
            else:
                _tel_event("rank_dead", rank=d, epoch=epoch)
        for j in sorted(joined):
            _tel_event("rank_rejoin", rank=j, epoch=epoch)
        _tel_event("mesh_reshape", epoch=epoch, world=len(new_members),
                   members=new_members, step=snap_step, planned=planned)
        _tel_event("elastic_recover", epoch=epoch, step=snap_step,
                   source=source, recovery_ms=round(ms, 2),
                   planned=planned)
        sys.stderr.write(
            f"[resilience] rank {self.rank}: gang reshaped to epoch "
            f"{epoch} world {len(new_members)} "
            f"({'planned, ' if planned else ''}source={source}, "
            f"snap_step={snap_step}, {ms:.0f} ms)\n")
        # end-of-assembly marker: departed ranks' RAM shards may be
        # pruned once every member has written this (see snapshot())
        self.kv.put_json(f"epoch_done/{epoch}/{self.rank}",
                         {"rank": self.rank, "t": time.time()})
        return RecoveryInfo(epoch=epoch, members=new_members,
                            snap_step=snap_step, source=source,
                            dead=dead, joined=joined, recovery_ms=ms,
                            shards=shards, full_state=full_state,
                            old_members=old_members, planned=planned)

    def join(self, timeout=None):
        """A (re)spawned rank asks the running gang for admission.

        Publishes ``join_req/<rank>``, waits for the proposer to admit
        it in a new epoch, then runs the shared ``recover`` path (ack,
        fetch every old member's shard from live RAM holders — the
        joiner has none of its own).  Returns the :class:`RecoveryInfo`
        to resume from, or None when the gang is fresh (nothing to
        join)."""
        self.start()    # writes/adopts the epoch record for fresh gangs
        cur = self.kv.get_json("epoch/current")
        if cur is None or self.rank in cur.get("members", []):
            # fresh gang (or a relaunch before any reshape): start()
            # already adopted the current epoch/membership
            return None
        self.kv.put_json(f"join_req/{self.rank}",
                         {"rank": self.rank, "t": time.time()})
        deadline = time.monotonic() + (timeout or self.reshape_timeout)
        while True:
            cur = self.kv.get_json("epoch/current") or {}
            if self.rank in cur.get("members", []):
                break
            if time.monotonic() > deadline:
                raise MXNetError(
                    f"rank {self.rank}: join request not admitted "
                    f"within {timeout or self.reshape_timeout}s")
            time.sleep(0.05)
        # participate in the admitting epoch's recover flow
        self.epoch = int(cur["epoch"]) - 1
        self.members = [int(r) for r in
                        cur.get("old_members", cur["members"])]
        self.detector = FailureDetector(self.kv, self.rank, self.members,
                                        timeout=self.detector.timeout)
        self.straggler.detector = self.detector
        return self.recover(None)

    def park_fenced(self, timeout=None, poll=0.25):
        """Minority-side parking after :class:`GangFenced`: keep
        heartbeating (the publisher thread already swallows KV errors),
        do NOT step, do NOT write anything durable — just probe the KV
        until it is reachable again, then rejoin through the normal
        ``join_req`` path, adopting the majority's state instead of our
        own.  Returns `join`'s :class:`RecoveryInfo`, or None when no
        newer epoch excluded us (we are still a member — resume
        stepping as-is).  Raises :class:`GangFenced` again if the
        partition outlives ``timeout`` seconds."""
        t0 = self._fenced_at if self._fenced_at is not None \
            else time.monotonic()
        deadline = None if timeout is None \
            else time.monotonic() + timeout
        while True:
            try:
                self.kv.get_json("epoch/current")    # read-only probe
                break
            except _gang_kv_errors():
                if deadline is not None and \
                        time.monotonic() > deadline:
                    raise self._fenced(
                        f"partition did not heal within {timeout}s")
            time.sleep(poll)
        fenced_ms = (time.monotonic() - t0) * 1000.0
        self._fenced_at = None
        _tel_event("partition_healed", rank=self.rank, epoch=self.epoch,
                   fenced_ms=round(fenced_ms, 2))
        sys.stderr.write(
            f"[resilience] rank {self.rank}: partition healed after "
            f"{fenced_ms:.0f} ms fenced; rejoining\n")
        return self.join()

    # -- protocol internals ----------------------------------------------------

    def _await_proposal(self, dead, joiners, ck, target_step=None,
                        planned=False):
        """Wait for (or, as the lowest-ranked survivor, write) the next
        epoch proposal.  Proposer promotion is implicit: if the lowest
        survivor dies before proposing, the detector adds it to ``dead``
        and the next-lowest takes over.  A planned reshape carries a
        ``target_step`` the proposal must be able to restore at (every
        member snapshotted there); the target is dropped halfway to the
        reshape timeout so a wedged drain degrades to lost steps rather
        than a dead gang.

        The proposal is STAGED at ``epoch/proposed`` with a plain put —
        advancing the fence now would reject healthy same-epoch
        snapshot writes mid-reshape; only the quorum-gated commit in
        `_await_acks` writes ``epoch/current`` and moves the fence."""
        deadline = time.monotonic() + self.reshape_timeout
        t_half = time.monotonic() + self.reshape_timeout / 2
        while True:
            cur = self.kv.get_json("epoch/current")
            if cur and int(cur.get("epoch", 0)) > self.epoch:
                return cur
            prop = self.kv.get_json("epoch/proposed")
            if prop and int(prop.get("epoch", 0)) > self.epoch:
                return prop
            dead |= self.detector.poll(force=True) & set(self.members)
            dead.discard(self.rank)
            survivors = sorted(set(self.members) - dead)
            if joiners:
                joiners = set(self._pending_joiners()) | set(joiners)
            if self._is_proposer(survivors):
                want = target_step \
                    if time.monotonic() < t_half else None
                proposal = self._make_proposal(dead, joiners,
                                               survivors, ck,
                                               target_step=want,
                                               planned=planned)
                if proposal is not None:
                    self.kv.put_json("epoch/proposed", proposal)
                    return proposal
            if time.monotonic() > deadline:
                raise MXNetError(
                    f"rank {self.rank}: no epoch proposal within "
                    f"{self.reshape_timeout}s (members "
                    f"{self.members}, dead {sorted(dead)})")
            time.sleep(0.05)

    def _make_proposal(self, dead, joiners, survivors, ck,
                       target_step=None, planned=False):
        new_members = sorted(set(survivors) | set(joiners))
        # common RAM restore point: the newest step that EVERY survivor
        # still holds (each advertises its retained steps, not just the
        # latest — a rank killed mid-snapshot-round leaves the others
        # one interval ahead, and the retention window is what lets
        # them meet one step back) and that each dead rank's live buddy
        # holds that rank's shard at
        common = None
        for r in survivors:
            info = self.kv.get_json(f"snap/{r}")
            if not info or int(info.get("epoch", -1)) != self.epoch:
                common = None
                break
            steps = set(int(s) for s in
                        info.get("steps") or [info["step"]])
            common = steps if common is None else common & steps
            if not common:
                break
        if common:
            for d in dead:
                holder = self.buddy_of(d, self.members)
                held = self.kv.get_json(f"held/{holder}/{d}")
                if holder in dead or not held \
                        or int(held.get("epoch", -1)) != self.epoch:
                    common = None
                    break
                common &= set(int(s) for s in held.get("steps", []))
                if not common:
                    break
        if target_step is not None and \
                not (common and max(common) >= int(target_step)):
            # planned reshape: restore point must be the agreed drain
            # step (zero lost steps) — a straggler's snapshot hasn't
            # landed yet, so don't propose; loop and retry
            return None
        ram_step = max(common) if common else None
        source = "peer" if ram_step is not None else "disk"
        disk_step = None
        if source == "disk":
            disk_step = ck.latest_step() if ck is not None else None
            if disk_step is None:
                raise MXNetError(
                    "elastic recovery: no common RAM snapshot and no "
                    "committed disk checkpoint to fall back to")
        for j in joiners:
            self.kv.delete(f"join_req/{j}")
        for d in dead:
            self.kv.delete(f"leave/{d}")
        self.kv.delete("admit/plan")
        return {"epoch": self.epoch + 1, "members": new_members,
                "old_members": list(self.members),
                "dead": sorted(dead), "joined": sorted(joiners),
                "snap_step": ram_step if source == "peer" else disk_step,
                "disk_step": disk_step, "source": source,
                "planned": bool(planned),
                "proposer": self.rank, "t": time.time()}

    def _await_acks(self, epoch, new_members, old_members=None,
                    proposal=None):
        """The ack round, quorum gate, and fenced commit.

        Every proposed member acks ``epoch_ack/<e>/<r>`` (written by
        `recover` before this call).  The epoch is COMMITTABLE only
        once the acks cover a strict majority of the PREVIOUS epoch's
        members — dead ranks count against, not for, so the minority
        side of a partition can never commit an epoch, no matter what
        its detector believes.  The lowest live proposed member then
        commits ``epoch/current`` with a fenced compare-and-swap
        (`put_if_epoch`) — which advances the fence and retires the
        staged ``epoch/proposed`` — and everyone returns once the
        committed membership has fully acked.  A deadline without
        quorum raises :class:`GangFenced` (park, rejoin after heal); a
        deadline with quorum but missing acks keeps the legacy
        :class:`MXNetError`."""
        from . import distributed
        deadline = time.monotonic() + self.reshape_timeout
        want = set(int(r) for r in new_members)
        prev = set(int(r) for r in
                   (old_members if old_members is not None
                    else self.members))
        quorum_of = prev or want
        quorum_ok = not self._quorum
        while True:
            cur = self.kv.get_json("epoch/current") or {}
            committed = int(cur.get("epoch", -1)) == epoch
            rec = cur if committed else \
                (self.kv.get_json("epoch/proposed") or {})
            if int(rec.get("epoch", -1)) == epoch:
                # the record is the source of truth: it may have been
                # amended below while we waited
                want = set(int(r) for r in rec.get("members", want))
                if self.rank not in want:
                    raise GangEvicted(
                        f"rank {self.rank}: epoch {epoch} was amended "
                        f"to exclude this rank; exiting cleanly")
            acked = set()
            for key, _ in self.kv.scan(f"epoch_ack/{epoch}"):
                try:
                    acked.add(int(key.rsplit("/", 1)[1]))
                except ValueError:
                    pass
            if not quorum_ok:
                quorum_ok = 2 * len(acked & quorum_of) > len(quorum_of)
            if committed and want <= acked:
                return
            # a proposed member that dies BETWEEN the proposal and its
            # ack would wedge this epoch forever (nobody re-detects it
            # once everyone is in recover).  The lowest live proposed
            # member amends the SAME epoch in place, shrinking the
            # membership to the ranks that can still ack; shard
            # assembly re-reads the amended record and falls back to
            # disk if the second death cost it a RAM holder.  The
            # amendment is a fenced CAS: a zombie amender carrying a
            # stale epoch is rejected server-side instead of clobbering
            # the committed record (the resilience.py:2066 race).
            newly_dead = (want - acked) & self.detector.poll(force=True)
            newly_dead.discard(self.rank)
            live = sorted(want - newly_dead)
            amender = bool(newly_dead) and live and self.rank == min(live)
            if amender and int(rec.get("epoch", -1)) == epoch:
                rec["members"] = live
                rec["dead"] = sorted(
                    set(int(d) for d in rec.get("dead", []))
                    | newly_dead)
                rec["joined"] = [j for j in rec.get("joined", [])
                                 if int(j) not in newly_dead]
                rec["t"] = time.time()
                try:
                    self._put_json_fenced(
                        "epoch/current" if committed else
                        "epoch/proposed", rec,
                        epoch if committed else self.epoch)
                except distributed.FencedWrite:
                    pass    # the fence moved under us: re-read above
                continue
            if not committed and quorum_ok and live \
                    and self.rank == min(live) and self.rank in acked:
                # quorum reached: commit.  put_if_epoch(epoch) advances
                # the fence, so every stale writer (minority proposer,
                # resumed zombie) is rejected from here on.
                commit = dict(rec) if int(rec.get("epoch", -1)) == epoch \
                    else dict(proposal or {})
                if int(commit.get("epoch", -1)) == epoch:
                    try:
                        self._put_json_fenced("epoch/current", commit,
                                              epoch)
                        self.kv.delete("epoch/proposed")
                    except distributed.FencedWrite:
                        pass    # a newer epoch beat us; re-read above
                    continue
            if time.monotonic() > deadline:
                if not committed and self._quorum and not quorum_ok:
                    raise self._fenced(
                        f"epoch {epoch} proposal gathered only "
                        f"{sorted(acked & quorum_of)} of previous "
                        f"members {sorted(quorum_of)} — no strict "
                        f"majority, refusing to commit (split-brain "
                        f"guard; MXTPU_QUORUM=0 overrides)")
                raise MXNetError(
                    f"epoch {epoch}: missing acks from "
                    f"{sorted(want - acked)} after "
                    f"{self.reshape_timeout}s")
            time.sleep(0.02)

    def _assemble_shards(self, snap_step, old_members, dead):
        """Every old rank's shard at ``snap_step``, from RAM: own copy,
        live peers serve their own, dead ranks' come from their buddies.
        Returns None if any fetch fails (caller degrades to disk)."""
        shards = {}
        for o in old_members:
            try:
                if o == self.rank:
                    st = self.peers.own_at(snap_step)
                elif o in dead:
                    holder = self.buddy_of(o, old_members)
                    st = self.peers.fetch(holder, o, snap_step)
                else:
                    st = self.peers.fetch(o, o, snap_step)
            except Exception as e:          # noqa: BLE001
                sys.stderr.write(
                    f"[resilience] peer shard fetch for rank {o} at "
                    f"step {snap_step} failed ({e}); falling back to "
                    f"disk\n")
                return None
            if st is None:
                return None
            shards[o] = st
        return shards


# -- autoscaling policy loop ---------------------------------------------------

class ScalePolicy:
    """Chooses the gang's world size from live telemetry.

    Grow: when the input pipeline is saturated — prefetch queue depth
    (telemetry gauge ``input.queue_depth``) at/above ``queue_high`` for
    ``window`` consecutive observations while the data-wait share stays
    at/below ``stall_low`` (compute-bound: more chips raise
    throughput) — write a ``scale/req`` record.  The launcher polls it
    and spawns extra ranks, which enter through the existing
    ``join_req`` path as a *scheduled* admit (zero lost steps).

    Shrink: ``on_preemption`` turns a preemption notice into a graceful
    drain — ``gang.plan_leave`` schedules this rank's departure a
    ``drain_margin`` of steps out, every member snapshots at the agreed
    step, and the reshape happens with no detection window.  The freed
    chips are announced (:func:`announce_freed_chips`) for the serving
    tier to claim.

    Knobs (ctor arg beats env beats default): ``MXTPU_SCALE_QUEUE_HIGH``
    (2.0), ``MXTPU_SCALE_STALL_LOW`` (0.1), ``MXTPU_SCALE_WINDOW`` (5),
    ``MXTPU_SCALE_COOLDOWN`` (30 s), ``MXTPU_SCALE_MAX_WORLD``,
    ``MXTPU_SCALE_MIN_WORLD`` (1).  The loop only runs when
    ``MXTPU_SCALE_POLICY`` is set (see :meth:`enabled`).
    """

    def __init__(self, gang, *, min_world=None, max_world=None,
                 queue_high=None, stall_low=None, window=None,
                 cooldown=None):
        def _env(name, default, cast=float):
            v = os.environ.get(name)
            return default if v in (None, "") else cast(v)

        self.gang = gang
        self.min_world = int(_env("MXTPU_SCALE_MIN_WORLD", 1, int)
                             if min_world is None else min_world)
        self.max_world = (_env("MXTPU_SCALE_MAX_WORLD", None,
                               lambda v: int(v))
                          if max_world is None else max_world)
        self.queue_high = float(_env("MXTPU_SCALE_QUEUE_HIGH", 2.0)
                                if queue_high is None else queue_high)
        self.stall_low = float(_env("MXTPU_SCALE_STALL_LOW", 0.1)
                               if stall_low is None else stall_low)
        self.window = max(1, int(_env("MXTPU_SCALE_WINDOW", 5, int)
                                 if window is None else window))
        self.cooldown = float(_env("MXTPU_SCALE_COOLDOWN", 30.0)
                              if cooldown is None else cooldown)
        self._hot = 0               # consecutive saturated observations
        self._last_req = 0.0        # monotonic time of last scale/req
        self.grow_requests = 0
        self.drains = 0

    @staticmethod
    def enabled():
        """MXTPU_SCALE_POLICY gates the whole loop (off by default)."""
        return os.environ.get("MXTPU_SCALE_POLICY", "").lower() \
            in ("1", "on", "true", "auto")

    def _queue_depth(self):
        try:
            from . import telemetry
        except ImportError:
            return None
        return telemetry.REGISTRY.gauge("input.queue_depth").value

    def observe(self, step, queue_depth=None, data_share=None):
        """Feed one step's signals; returns ``"grow"`` when a scale-up
        request was just published, else None.  ``queue_depth`` defaults
        to the live ``input.queue_depth`` gauge."""
        if queue_depth is None:
            queue_depth = self._queue_depth()
        if queue_depth is None:
            return None
        saturated = queue_depth >= self.queue_high and \
            (data_share is None or data_share <= self.stall_low)
        self._hot = self._hot + 1 if saturated else 0
        if self._hot < self.window:
            return None
        now = time.monotonic()
        if now - self._last_req < self.cooldown:
            return None
        world = len(self.gang.members)
        want = world + 1
        if self.max_world is not None and want > int(self.max_world):
            return None
        req = self.gang.kv.get_json("scale/req")
        if isinstance(req, dict) and int(req.get("want_world", 0)) \
                >= want:
            return None     # an equal-or-larger request is pending
        self.gang.kv.put_json(
            "scale/req", {"want_world": want, "step": int(step),
                          "reason": "input_saturated",
                          "queue_depth": float(queue_depth),
                          "t": time.time()})
        _tel_event("scale_up", rank=self.gang.rank, step=int(step),
                   want_world=want, world=world,
                   queue_depth=float(queue_depth))
        self._last_req = now
        self._hot = 0
        self.grow_requests += 1
        return "grow"

    def on_preemption(self, step):
        """Preemption notice → graceful drain: schedule this rank's
        planned departure and announce the chips it frees.  Returns the
        agreed departure step, or None when the gang is already at
        ``min_world``."""
        if len(self.gang.members) <= self.min_world:
            return None
        at = self.gang.plan_leave(int(step) + self.gang.drain_margin)
        _tel_event("scale_down", rank=self.gang.rank, step=int(step),
                   at_step=at, world=len(self.gang.members),
                   planned=True)
        self.drains += 1
        return at


def announce_freed_chips(kv, rank, *, step=None, count=1, addr=None):
    """Publish that ``rank``'s chips are free (post-drain): the serving
    tier's FleetWatcher claims ``chips/freed/<rank>`` and spawns a
    replica on them — one elastically partitioned mesh shared by
    training and serving."""
    rec = {"rank": int(rank), "count": int(count), "t": time.time()}
    if step is not None:
        rec["step"] = int(step)
    if addr is not None:
        rec["addr"] = addr
    kv.put_json(f"chips/freed/{rank}", rec)
    _tel_event("chips_freed", rank=int(rank), count=int(count),
               step=step)
    return rec


def _tel_count(name, n=1):
    """Guarded telemetry counter (same standalone-load story as
    `_tel_event`)."""
    try:
        from . import telemetry
    except ImportError:
        return
    telemetry.count(name, n)
