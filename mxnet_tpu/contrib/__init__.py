"""Contrib namespace (reference: python/mxnet/contrib/)."""

from . import quantization
from .. import amp  # reference path: mx.contrib.amp → mx.amp
