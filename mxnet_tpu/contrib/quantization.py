"""Model quantization flow.

Reference parity: python/mxnet/contrib/quantization.py — quantize_model
(calibration-based int8 conversion, ≥1.2): symbol-graph rewrite inserting
quantize_v2 → quantized_conv/quantized_fully_connected → dequantize
around the MXU-heavy ops, with 'naive' (min/max) and 'entropy'
(KL-optimal threshold) calibration, plus a gluon front door
(quantize_net) that composes trace_block → quantize_model → SymbolBlock.

TPU flow: int8×int8→int32 runs on the MXU (ops/quantization.py);
ranges ride the graph as scalar-constant symbols exactly like the
reference's (data, min, max) triples.
"""

from __future__ import annotations

import numpy as _np

from ..base import MXNetError

_QUANTIZABLE = {"Convolution", "FullyConnected"}


class CalibrationCollector:
    """Collects per-layer activation ranges (reference: the calibration
    pass of quantize_model; 'naive' min/max and percentile modes)."""

    def __init__(self, mode="naive", percentile=99.99):
        assert mode in ("naive", "percentile")
        self.mode = mode
        self.percentile = percentile
        self.ranges = {}

    def collect(self, name, array):
        a = array.asnumpy() if hasattr(array, "asnumpy") \
            else _np.asarray(array)
        if self.mode == "naive":
            lo, hi = float(a.min()), float(a.max())
        else:
            lo = float(_np.percentile(a, 100 - self.percentile))
            hi = float(_np.percentile(a, self.percentile))
        if name in self.ranges:
            plo, phi = self.ranges[name]
            lo, hi = min(lo, plo), max(hi, phi)
        self.ranges[name] = (lo, hi)
        return self.ranges[name]


def _smooth_distribution(p, eps=0.0001):
    """Reference: _smooth_distribution — move eps mass onto zero bins so
    KL is finite, taken proportionally from nonzero bins."""
    is_zeros = (p == 0).astype(_np.float64)
    is_nonzeros = (p != 0).astype(_np.float64)
    n_zeros = int(is_zeros.sum())
    n_nonzeros = p.size - n_zeros
    if not n_nonzeros:
        return None
    eps1 = eps * n_zeros / n_nonzeros
    hist = p.astype(_np.float64)
    hist += eps * is_zeros + (-eps1) * is_nonzeros
    if (hist <= 0).any():
        return None
    return hist


def _get_optimal_threshold(arr, num_bins=2001, num_quantized_bins=255):
    """KL-divergence-optimal |threshold| for int8 (reference:
    _get_optimal_threshold in python/mxnet/contrib/quantization.py —
    the TensorRT-style entropy calibration).

    The load-bearing subtlety (reference keeps it too): the candidate
    distribution ``p`` has the clipped outlier mass merged into its edge
    bin while ``q`` is built from the UNMERGED histogram — so a
    too-small threshold is penalized for the mass it throws away.
    """
    a = _np.abs(_np.asarray(arr, dtype=_np.float64).ravel())
    amax = float(a.max()) if a.size else 0.0
    if amax == 0:
        return 1e-8
    hist, edges = _np.histogram(a, bins=num_bins, range=(0, amax))
    hist = hist.astype(_np.float64)

    best_kl, best_t = _np.inf, amax
    step = max(1, (num_bins - num_quantized_bins) // 128)
    for i in range(num_quantized_bins, num_bins + 1, step):
        t = edges[i] if i < len(edges) else amax
        sliced = hist[:i]
        if sliced.sum() == 0:
            continue
        p = sliced.copy()
        p[-1] += hist[i:].sum()  # clipped mass -> edge bin (p only)
        num_merged = i // num_quantized_bins
        qb = _np.add.reduceat(
            sliced[:num_quantized_bins * num_merged],
            _np.arange(0, num_quantized_bins * num_merged, num_merged))
        qb[-1] += sliced[num_quantized_bins * num_merged:].sum()
        q = _np.zeros(i)
        is_nz = sliced != 0
        for j in range(num_quantized_bins):
            lo = j * num_merged
            hi = i if j == num_quantized_bins - 1 else lo + num_merged
            nz = is_nz[lo:hi]
            n = int(nz.sum())
            if n:
                q[lo:hi][nz] = qb[j] / n
        ps = _smooth_distribution(p)
        qs = _smooth_distribution(q)
        if ps is None or qs is None:
            continue
        ps = ps / ps.sum()
        qs = qs / qs.sum()
        kl = float(_np.sum(ps * _np.log(ps / qs)))
        if kl < best_kl:
            best_kl, best_t = kl, t
    return float(best_t)


def _collect_calib_ranges(sym, points, data_names, calib_data,
                          num_calib_examples, calib_mode, params=None):
    """Run the float graph on calibration batches, recording ranges at
    each quantize-insertion point (reference: collect_layer_output)."""
    from .. import symbol as _sym_mod
    from ..ndarray.ndarray import NDArray

    group = _sym_mod.Group([p for _, p in points])
    # naive streams a running (min, max); entropy keeps a bounded random
    # subsample per point (the KL search needs the value distribution,
    # but not every activation of every batch in host RAM)
    minmax = {}
    samples = {name: [] for name, _ in points}
    budget = 1 << 16  # per-point per-batch subsample cap
    rng = _np.random.RandomState(0)
    seen = 0
    for batch in calib_data:
        x = batch.data[0] if hasattr(batch, "data") else batch
        feed = dict(params or {})
        feed[data_names[0]] = x
        env = {k: (v._data if isinstance(v, NDArray) else v)
               for k, v in feed.items()}
        outs = group.eval_raw(**env)
        outs = outs if isinstance(outs, (list, tuple)) else [outs]
        for (name, _), o in zip(points, outs):
            a = _np.asarray(o).ravel()
            lo, hi = float(a.min()), float(a.max())
            plo, phi = minmax.get(name, (lo, hi))
            minmax[name] = (min(lo, plo), max(hi, phi))
            if calib_mode == "entropy":
                if a.size > budget:
                    a = a[rng.randint(0, a.size, budget)]
                samples[name].append(a.astype(_np.float32))
        seen += int(x.shape[0])
        if num_calib_examples is not None and seen >= num_calib_examples:
            break
    ranges = {}
    for name, _ in points:
        if name not in minmax:
            continue
        if calib_mode == "entropy":
            allv = _np.concatenate(samples[name])
            t = _get_optimal_threshold(allv)
            ranges[name] = (-t, t)
        else:
            ranges[name] = minmax[name]
    return ranges


def quantize_model(sym, arg_params, aux_params, data_names=("data",),
                   ctx=None, excluded_sym_names=(), calib_mode="none",
                   calib_data=None, num_calib_examples=None,
                   quantized_dtype="int8", **kwargs):
    """Rewrite a float Symbol graph for int8 inference (reference:
    quantize_model, python/mxnet/contrib/quantization.py).

    Returns (qsym, qarg_params, aux_params): Convolution/FullyConnected
    nodes become quantize_v2 → quantized_* → dequantize chains, weights
    are offline-quantized to int8 in qarg_params, and — with
    calib_mode 'naive'/'entropy' — activation quantizers carry static
    calibrated ranges so inference needs no runtime min/max pass.
    """
    from .. import ndarray as _nd
    from .. import symbol as _sym_mod
    from ..ndarray.ndarray import NDArray

    if quantized_dtype != "int8":
        raise MXNetError("TPU quantization supports int8 (MXU int8 path)")
    if calib_mode not in ("none", "naive", "entropy"):
        raise MXNetError(f"unknown calib_mode {calib_mode}")
    excluded = set(excluded_sym_names or ())
    data_names = ([data_names] if isinstance(data_names, str)
                  else list(data_names))

    topo = sym._topo()
    # pre-pass: the float input symbol of every quantizable node
    points = []
    for node in topo:
        if _is_quantizable(node, excluded) and node.inputs:
            points.append((node.name, _as_entry(node.inputs[0])))

    calib_ranges = {}
    if calib_mode in ("naive", "entropy"):
        if calib_data is None:
            raise MXNetError(f"calib_mode='{calib_mode}' needs calib_data")
        bound = {**(arg_params or {}), **(aux_params or {})}
        calib_ranges = _collect_calib_ranges(
            sym, points, data_names, calib_data, num_calib_examples,
            calib_mode, params=bound)

    qarg_params = dict(arg_params or {})
    bias_ranges = {}  # bias name -> absmax (shared-bias reuse guard)
    rebuilt = {}  # original node name -> rebuilt Symbol (node-level)

    def lookup(entry):
        """Rebuilt symbol for one original input entry."""
        node = entry
        r = rebuilt[node.name]
        if node.out_index:
            return r[node.out_index]
        return r

    for node in topo:
        if node.op is None:  # variable
            v = _sym_mod.var(node.name)
            v.attrs.update(node.attrs)
            v._attr_dict.update(node._attr_dict)
            rebuilt[node.name] = v
            continue
        ins = [lookup(_as_entry(i)) for i in node.inputs]
        if _is_quantizable(node, excluded):
            data_s = ins[0]
            w_entry = _as_entry(node.inputs[1])
            w_name = w_entry.name
            bias_s = ins[2] if len(ins) > 2 else None
            no_bias = bool(node.attrs.get("no_bias", False)) \
                or bias_s is None

            # offline weight quantization
            w_nd = qarg_params.get(w_name)
            if w_nd is None:
                raise MXNetError(
                    f"quantize_model: missing weight param {w_name}")
            if w_name + "_max" in qarg_params:
                # weight shared by two quantizable nodes: already int8
                # codes — re-quantizing the CODES would compute scales
                # from ~127-valued data; reuse the stored range instead
                w_absmax = float(_np.asarray(
                    qarg_params[w_name + "_max"].asnumpy()
                    if isinstance(qarg_params[w_name + "_max"], NDArray)
                    else qarg_params[w_name + "_max"])[0])
            else:
                w_np = w_nd.asnumpy() if isinstance(w_nd, NDArray) \
                    else _np.asarray(w_nd)
                w_absmax = float(max(abs(w_np.min()), abs(w_np.max()),
                                     1e-8))
                w_q = _np.clip(_np.round(w_np * (127.0 / w_absmax)),
                               -127, 127).astype(_np.int8)
                qarg_params[w_name] = _nd.array(w_q, dtype="int8")
                qarg_params[w_name + "_min"] = _nd.array([-w_absmax])
                qarg_params[w_name + "_max"] = _nd.array([w_absmax])
            w_var = rebuilt[w_name]
            wmin = _sym_mod.var(w_name + "_min")
            wmax = _sym_mod.var(w_name + "_max")
            rebuilt.setdefault(w_name + "_min", wmin)
            rebuilt.setdefault(w_name + "_max", wmax)

            qkw = {}
            if node.name in calib_ranges:
                lo, hi = calib_ranges[node.name]
                qkw = {"min_calib_range": lo, "max_calib_range": hi}
            qz = _sym_mod.apply_op("_contrib_quantize_v2", data_s,
                                   name=node.name + "_data_quantize",
                                   **qkw)
            qdata, dmin, dmax = qz[0], qz[1], qz[2]

            if not no_bias:
                # bias is quantized to int8 CODES offline — the
                # quantized ops' contract (ops/quantization.py) is int8
                # bias + min/max, mirroring the reference's
                # quantized-bias inputs
                b_entry = _as_entry(node.inputs[2])
                if b_entry.name in bias_ranges:
                    # shared bias: already int8 codes — reuse the range
                    # (same defect class as the shared-weight guard)
                    b_absmax = bias_ranges[b_entry.name]
                else:
                    b_nd = qarg_params.get(b_entry.name)
                    b_np = b_nd.asnumpy() if isinstance(b_nd, NDArray) \
                        else _np.asarray(b_nd)
                    b_absmax = float(max(abs(b_np.min()),
                                         abs(b_np.max()), 1e-8))
                    b_q = _np.clip(_np.round(b_np * (127.0 / b_absmax)),
                                   -127, 127).astype(_np.int8)
                    qarg_params[b_entry.name] = _nd.array(b_q,
                                                          dtype="int8")
                    bias_ranges[b_entry.name] = b_absmax
                from ..symbol.symbol import _scalar_sym
                bmin = _scalar_sym(-b_absmax)
                bmax = _scalar_sym(b_absmax)
            op_attrs = {k: v for k, v in node.attrs.items()
                        if k not in ("cudnn_tune", "cudnn_off",
                                     "workspace", "dilate", "layout")}
            qop = ("_contrib_quantized_conv"
                   if node.op == "Convolution"
                   else "_contrib_quantized_fully_connected")
            if no_bias:
                qnode = _sym_mod.apply_op(
                    qop, qdata, w_var, None, dmin, dmax, wmin, wmax,
                    name=node.name + "_quantized", **op_attrs)
            else:
                qnode = _sym_mod.apply_op(
                    qop, qdata, w_var, bias_s, dmin, dmax, wmin, wmax,
                    bmin, bmax, name=node.name + "_quantized", **op_attrs)
            deq = _sym_mod.apply_op(
                "_contrib_dequantize", qnode[0], qnode[1], qnode[2],
                name=node.name + "_dequantize")
            rebuilt[node.name] = deq
        else:
            rebuilt[node.name] = _sym_mod.apply_op(
                node.op, *ins, name=node.name, **node.attrs)

    head = rebuilt[sym.name]
    qsym = head[sym.out_index] if sym.out_index else head
    return qsym, qarg_params, dict(aux_params or {})


def _as_entry(x):
    """Inputs may be stored as Symbol entries already."""
    return x


def _nontrivial_dilate(attrs):
    d = attrs.get("dilate")
    if d is None:
        return False
    if isinstance(d, str):
        d = d.strip("()[] ").replace(",", " ").split()
    try:
        return any(int(v) != 1 for v in d)
    except (TypeError, ValueError):
        return True  # unparseable: be conservative, keep it float


def _is_quantizable(node, excluded):
    """ADVICE r3: quantized_conv has no dilation support — a dilated
    Convolution must stay float instead of being silently rewritten
    into a non-dilated int8 conv (wrong results)."""
    if node.op not in _QUANTIZABLE or node.name in excluded:
        return False
    if node.op == "Convolution" and _nontrivial_dilate(node.attrs):
        return False
    return True


def quantize_net(network, calib_data=None, calib_mode="naive",
                 num_calib_examples=None, excluded_sym_names=(),
                 data_shapes=None, **kwargs):
    """Gluon front door (reference: quantize_net, ≥1.6): trace the
    hybridized block to a Symbol, rewrite for int8, return a SymbolBlock
    running the quantized graph."""
    from .. import symbol as _sym_mod
    from ..gluon.block import SymbolBlock

    sym = _sym_mod.trace_block(network)
    params = network.collect_params()
    arg_params, aux_params = {}, {}
    for name, p in params.items():
        (aux_params if p.grad_req == "null" else arg_params)[name] = \
            p.data()
    qsym, qarg, qaux = quantize_model(
        sym, arg_params, aux_params, calib_mode=calib_mode,
        calib_data=calib_data, num_calib_examples=num_calib_examples,
        excluded_sym_names=excluded_sym_names, **kwargs)
    sb = SymbolBlock(qsym, [_sym_mod.var("data")])
    all_q = {**qarg, **qaux}
    for name, p in sb.params.items():
        if name in all_q:
            p._load_init(all_q[name], None, cast_dtype=False)
    return sb


def quantize_block(block, calib_data=None, num_calib_batches=5,
                   calib_mode="naive"):
    """Calibrate + mark a gluon block for int8 inference.

    Returns (block, calib_ranges).  Dense/Conv weights get static ranges
    from their values; activations get ranges from calibration batches.
    """
    collector = CalibrationCollector(mode=calib_mode)
    for name, param in block.collect_params().items():
        if name.endswith("weight"):
            collector.collect(name, param.data())
    if calib_data is not None:
        count = 0
        for batch in calib_data:
            x = batch.data[0] if hasattr(batch, "data") else batch
            collector.collect("__input__", x)
            out = block(x)
            first = out[0] if isinstance(out, tuple) else out
            collector.collect("__output__", first)
            count += 1
            if count >= num_calib_batches:
                break
    block._quant_ranges = dict(collector.ranges)
    return block, collector.ranges
