"""Model quantization flow.

Reference parity: python/mxnet/contrib/quantization.py — quantize_model
(calibration-based int8 conversion, ≥1.2).

TPU flow: calibrate activation ranges by running batches through the fp
model (min/max or percentile), then wrap Dense/Conv layers so inference
runs the int8 MXU path (ops/quantization.py).
"""

from __future__ import annotations

import numpy as _np

from ..base import MXNetError


class CalibrationCollector:
    """Collects per-layer activation ranges (reference: the calibration
    pass of quantize_model; 'naive' min/max and percentile modes)."""

    def __init__(self, mode="naive", percentile=99.99):
        assert mode in ("naive", "percentile")
        self.mode = mode
        self.percentile = percentile
        self.ranges = {}

    def collect(self, name, array):
        a = array.asnumpy() if hasattr(array, "asnumpy") \
            else _np.asarray(array)
        if self.mode == "naive":
            lo, hi = float(a.min()), float(a.max())
        else:
            lo = float(_np.percentile(a, 100 - self.percentile))
            hi = float(_np.percentile(a, self.percentile))
        if name in self.ranges:
            plo, phi = self.ranges[name]
            lo, hi = min(lo, plo), max(hi, phi)
        self.ranges[name] = (lo, hi)
        return self.ranges[name]


def quantize_block(block, calib_data=None, num_calib_batches=5,
                   calib_mode="naive"):
    """Calibrate + mark a gluon block for int8 inference.

    Returns (block, calib_ranges).  Dense/Conv weights get static ranges
    from their values; activations get ranges from calibration batches.
    """
    from ..gluon import nn

    collector = CalibrationCollector(mode=calib_mode)
    # weight ranges are static
    for name, param in block.collect_params().items():
        if name.endswith("weight"):
            collector.collect(name, param.data())
    # activation ranges from calibration data
    if calib_data is not None:
        count = 0
        for batch in calib_data:
            x = batch.data[0] if hasattr(batch, "data") else batch
            collector.collect("__input__", x)
            out = block(x)
            first = out[0] if isinstance(out, tuple) else out
            collector.collect("__output__", first)
            count += 1
            if count >= num_calib_batches:
                break
    block._quant_ranges = dict(collector.ranges)
    return block, collector.ranges


def quantize_model(sym, arg_params, aux_params, data_names=("data",),
                   ctx=None, calib_mode="none", calib_data=None,
                   num_calib_examples=None, quantized_dtype="int8",
                   **kwargs):
    """Symbol-path API shell (reference signature parity).  Graph rewrite
    of arbitrary symbols into quantized ops is a later milestone; the
    gluon path (quantize_block) is the supported flow."""
    raise NotImplementedError(
        "symbolic quantize_model graph rewriting is not implemented yet; "
        "use contrib.quantization.quantize_block on a gluon model "
        "(int8 ops: mx.nd.quantize/quantized_fully_connected/"
        "quantized_conv)")
