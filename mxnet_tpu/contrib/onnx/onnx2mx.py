"""ONNX → Symbol import (reference: python/mxnet/contrib/onnx/onnx2mx
import_model).  Inverse of mx2onnx for the supported op table."""

from __future__ import annotations

import numpy as _np

from ...base import MXNetError
from . import onnx_minimal_pb2 as _pb

_NP_DT = {_pb.TensorProto.FLOAT: _np.float32,
          _pb.TensorProto.DOUBLE: _np.float64,
          _pb.TensorProto.FLOAT16: _np.float16,
          _pb.TensorProto.INT32: _np.int32,
          _pb.TensorProto.INT64: _np.int64,
          _pb.TensorProto.INT8: _np.int8,
          _pb.TensorProto.UINT8: _np.uint8,
          _pb.TensorProto.BOOL: _np.bool_}


def _tensor_to_np(t):
    dt = _NP_DT.get(t.data_type)
    if dt is None:
        raise MXNetError(f"onnx import: tensor dtype {t.data_type}")
    if t.raw_data:
        arr = _np.frombuffer(t.raw_data, dtype=dt)
    elif t.float_data:
        arr = _np.asarray(list(t.float_data), dtype=dt)
    elif t.int64_data:
        arr = _np.asarray(list(t.int64_data), dtype=dt)
    elif t.int32_data:
        arr = _np.asarray(list(t.int32_data), dtype=dt)
    else:
        arr = _np.zeros(0, dt)
    return arr.reshape(tuple(t.dims))


def _attrs(node):
    out = {}
    for a in node.attribute:
        if a.type == _pb.AttributeProto.INT:
            out[a.name] = int(a.i)
        elif a.type == _pb.AttributeProto.FLOAT:
            out[a.name] = float(a.f)
        elif a.type == _pb.AttributeProto.STRING:
            out[a.name] = a.s.decode()
        elif a.type == _pb.AttributeProto.INTS:
            out[a.name] = [int(v) for v in a.ints]
        elif a.type == _pb.AttributeProto.FLOATS:
            out[a.name] = [float(v) for v in a.floats]
        elif a.type == _pb.AttributeProto.TENSOR:
            out[a.name] = _tensor_to_np(a.t)
    return out


def _depair(pads):
    """ONNX pads [b0,b1,e0,e1] -> symmetric mxnet pad (p0,p1)."""
    if not pads:
        return (0, 0)
    half = len(pads) // 2
    begin, end = pads[:half], pads[half:]
    if list(begin) != list(end):
        raise MXNetError(f"onnx import: asymmetric pads {pads}")
    return tuple(begin)


def import_model(model_file):
    """Load an .onnx file → (sym, arg_params, aux_params).  Reference:
    onnx_mxnet.import_model."""
    from ... import ndarray as _nd
    from ... import symbol as _sym_mod
    from ...symbol.symbol import _scalar_sym

    model = _pb.ModelProto()
    with open(model_file, "rb") as f:
        model.ParseFromString(f.read())
    g = model.graph

    inits = {t.name: _tensor_to_np(t) for t in g.initializer}
    arg_params, aux_params = {}, {}
    sym_of = {}

    graph_inputs = [vi.name for vi in g.input if vi.name not in inits]
    for nm in graph_inputs:
        sym_of[nm] = _sym_mod.var(nm)

    consumed_as_const = set()

    def sym_in(name):
        if name in sym_of:
            return sym_of[name]
        if name in inits:
            v = _sym_mod.var(name)
            sym_of[name] = v
            return v
        raise MXNetError(f"onnx import: undefined input {name}")

    for node in g.node:
        op = node.op_type
        a = _attrs(node)
        ins = list(node.input)
        out = node.output[0]

        def mk(mxop, inputs, **kw):
            return _sym_mod.apply_op(mxop, *inputs, name=out, **kw)

        if op == "Conv":
            s = mk("Convolution", [sym_in(i) for i in ins],
                   kernel=tuple(a.get("kernel_shape", ())),
                   stride=tuple(a.get("strides", (1, 1))),
                   dilate=tuple(a.get("dilations", (1, 1))),
                   pad=_depair(a.get("pads", ())),
                   num_group=a.get("group", 1),
                   num_filter=int(inits[ins[1]].shape[0])
                   if ins[1] in inits else 0,
                   no_bias=len(ins) < 3)
        elif op == "ConvTranspose":
            s = mk("Deconvolution", [sym_in(i) for i in ins],
                   kernel=tuple(a.get("kernel_shape", ())),
                   stride=tuple(a.get("strides", (1, 1))),
                   pad=_depair(a.get("pads", ())),
                   num_group=a.get("group", 1),
                   no_bias=len(ins) < 3)
        elif op == "Gemm":
            if a.get("transB", 0) != 1 or a.get("transA", 0) != 0:
                raise MXNetError("onnx import: Gemm needs transB=1")
            s = mk("FullyConnected", [sym_in(i) for i in ins],
                   num_hidden=int(inits[ins[1]].shape[0])
                   if ins[1] in inits else 0,
                   no_bias=len(ins) < 3, flatten=False)
        elif op == "BatchNormalization":
            s = mk("BatchNorm", [sym_in(i) for i in ins[:5]],
                   eps=a.get("epsilon", 1e-5),
                   momentum=a.get("momentum", 0.9), fix_gamma=False)
            for aux_nm in ins[3:5]:
                if aux_nm in inits:
                    aux_params[aux_nm] = _nd.array(inits[aux_nm])
                    consumed_as_const.add(aux_nm)
        elif op in ("MaxPool", "AveragePool"):
            s = mk("Pooling", [sym_in(ins[0])],
                   kernel=tuple(a.get("kernel_shape", ())),
                   stride=tuple(a.get("strides", (1, 1))),
                   pad=_depair(a.get("pads", ())),
                   pool_type="max" if op == "MaxPool" else "avg",
                   count_include_pad=bool(a.get("count_include_pad", 1)))
        elif op in ("GlobalMaxPool", "GlobalAveragePool"):
            s = mk("Pooling", [sym_in(ins[0])],
                   kernel=(1, 1), global_pool=True,
                   pool_type="max" if op == "GlobalMaxPool" else "avg")
        elif op == "Flatten":
            s = mk("Flatten", [sym_in(ins[0])])
        elif op == "Dropout":
            s = mk("Dropout", [sym_in(ins[0])], p=a.get("ratio", 0.5))
        elif op == "Softmax":
            s = mk("softmax", [sym_in(ins[0])], axis=a.get("axis", -1))
        elif op == "Concat":
            s = mk("Concat", [sym_in(i) for i in ins],
                   dim=a.get("axis", 1))
        elif op == "Clip":
            s = mk("clip", [sym_in(ins[0])],
                   a_min=a.get("min", -3.4e38), a_max=a.get("max", 3.4e38))
        elif op == "Reshape":
            shape = inits.get(ins[1])
            if shape is None:
                raise MXNetError("onnx import: dynamic Reshape shape")
            consumed_as_const.add(ins[1])
            s = mk("Reshape", [sym_in(ins[0])],
                   shape=tuple(int(v) for v in shape))
        elif op == "Gather":
            s = mk("Embedding", [sym_in(ins[1]), sym_in(ins[0])],
                   input_dim=int(inits[ins[0]].shape[0])
                   if ins[0] in inits else 0,
                   output_dim=int(inits[ins[0]].shape[1])
                   if ins[0] in inits else 0)
        elif op == "Transpose":
            s = mk("transpose", [sym_in(ins[0])],
                   axes=tuple(a.get("perm", ())))
        elif op == "Unsqueeze":
            s = mk("expand_dims", [sym_in(ins[0])],
                   axis=int(a.get("axes", [0])[0]))
        elif op in ("Relu", "Sigmoid", "Tanh", "Softplus", "Erf"):
            table = {"Relu": "relu", "Sigmoid": "sigmoid",
                     "Tanh": "tanh", "Softplus": "softrelu",
                     "Erf": "erf"}
            s = mk("Activation", [sym_in(ins[0])], act_type=table[op])
        elif op == "LeakyRelu":
            s = mk("LeakyReLU", [sym_in(ins[0])], act_type="leaky",
                   slope=a.get("alpha", 0.01))
        elif op == "Elu":
            s = mk("LeakyReLU", [sym_in(ins[0])], act_type="elu",
                   slope=a.get("alpha", 1.0))
        elif op in ("Add", "Sub", "Mul", "Div", "Pow"):
            table = {"Add": "broadcast_add", "Sub": "broadcast_sub",
                     "Mul": "broadcast_mul", "Div": "broadcast_div",
                     "Pow": "broadcast_power"}
            s = mk(table[op], [sym_in(i) for i in ins])
        elif op == "MatMul":
            s = mk("dot", [sym_in(i) for i in ins])
        elif op == "Log":
            s = mk("log", [sym_in(ins[0])])
        elif op == "Exp":
            s = mk("exp", [sym_in(ins[0])])
        elif op == "Identity":
            s = sym_in(ins[0])
        elif op in ("ReduceMean", "ReduceSum", "ReduceMax", "ReduceMin",
                    "ReduceProd"):
            table = {"ReduceMean": "mean", "ReduceSum": "sum",
                     "ReduceMax": "max", "ReduceMin": "min",
                     "ReduceProd": "prod"}
            ax = a.get("axes")
            s = mk(table[op], [sym_in(ins[0])],
                   axis=tuple(ax) if ax else None,
                   keepdims=bool(a.get("keepdims", 1)))
        else:
            raise MXNetError(f"onnx import: unsupported op {op}")
        sym_of[out] = s

    for nm, arr in inits.items():
        if nm in aux_params or nm in consumed_as_const:
            continue
        if nm in sym_of:  # actually referenced by the graph
            arg_params[nm] = _nd.array(arr)

    out_name = g.output[0].name
    return sym_of[out_name], arg_params, aux_params


def import_to_gluon(model_file, ctx=None):
    """Reference: onnx_mxnet.import_to_gluon — returns a SymbolBlock."""
    from ...gluon.block import SymbolBlock
    from ... import symbol as _sym_mod

    sym, arg_params, aux_params = import_model(model_file)
    free = [n for n in sym.list_inputs()
            if n not in arg_params and n not in aux_params]
    sb = SymbolBlock(sym, [_sym_mod.var(n) for n in free])
    allp = {**arg_params, **aux_params}
    for name, p in sb.params.items():
        if name in allp:
            p._load_init(allp[name], ctx, cast_dtype=True)
    return sb
