"""Symbol → ONNX export (reference: python/mxnet/contrib/onnx/mx2onnx
export_model / MXNetGraph.create_onnx_graph_proto).

Targets opset 9 (attribute-style Clip/Dropout, input-style Reshape).
"""

from __future__ import annotations

import numpy as _np

from ...base import MXNetError
from . import onnx_minimal_pb2 as _pb

_OPSET = 9

_DT = {"float32": _pb.TensorProto.FLOAT, "float64": _pb.TensorProto.DOUBLE,
       "float16": _pb.TensorProto.FLOAT16, "int32": _pb.TensorProto.INT32,
       "int64": _pb.TensorProto.INT64, "int8": _pb.TensorProto.INT8,
       "uint8": _pb.TensorProto.UINT8, "bool": _pb.TensorProto.BOOL,
       "bfloat16": _pb.TensorProto.BFLOAT16}

_ACT = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
        "softrelu": "Softplus", "softsign": "Softsign", "gelu": "Gelu",
        "erf": "Erf"}

_ELEM = {"broadcast_add": "Add", "elemwise_add": "Add", "_plus": "Add",
         "broadcast_sub": "Sub", "elemwise_sub": "Sub",
         "broadcast_mul": "Mul", "elemwise_mul": "Mul",
         "broadcast_div": "Div", "elemwise_div": "Div",
         "broadcast_maximum": "Max", "broadcast_minimum": "Min",
         "broadcast_power": "Pow", "dot": "MatMul"}

_REDUCE = {"mean": "ReduceMean", "sum": "ReduceSum", "max": "ReduceMax",
           "min": "ReduceMin", "prod": "ReduceProd"}

_UNARY = {"exp": "Exp", "log": "Log", "sqrt": "Sqrt", "abs": "Abs",
          "negative": "Neg", "floor": "Floor", "ceil": "Ceil",
          "relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
          "erf": "Erf", "identity": "Identity", "_copy": "Identity"}


def _attr(node, name, value):
    a = node.attribute.add()
    a.name = name
    if isinstance(value, bool):
        a.i = int(value)
        a.type = _pb.AttributeProto.INT
    elif isinstance(value, int):
        a.i = value
        a.type = _pb.AttributeProto.INT
    elif isinstance(value, float):
        a.f = value
        a.type = _pb.AttributeProto.FLOAT
    elif isinstance(value, str):
        a.s = value.encode()
        a.type = _pb.AttributeProto.STRING
    elif isinstance(value, (list, tuple)):
        if value and isinstance(value[0], float):
            a.floats.extend(value)
            a.type = _pb.AttributeProto.FLOATS
        else:
            a.ints.extend(int(v) for v in value)
            a.type = _pb.AttributeProto.INTS
    else:
        raise MXNetError(f"onnx attr {name}: unsupported {type(value)}")


def _tensor(name, arr):
    t = _pb.TensorProto()
    t.name = name
    arr = _np.ascontiguousarray(arr)
    t.dims.extend(arr.shape)
    dt = _DT.get(str(arr.dtype))
    if dt is None:
        raise MXNetError(f"onnx export: dtype {arr.dtype} unsupported")
    t.data_type = dt
    t.raw_data = arr.tobytes()
    return t


def _pair(v, n=2):
    if isinstance(v, (list, tuple)):
        return [int(x) for x in v]
    return [int(v)] * n


def _scalar_value(sym):
    return sym.attrs.get("__scalar__")


class _Ctx:
    def __init__(self, graph, params):
        self.graph = graph
        self.params = params
        self.names = {}        # id(sym-node) -> output name
        self.extra_init = {}   # name -> ndarray (generated consts)
        self.counter = [0]

    def fresh(self, hint):
        self.counter[0] += 1
        return f"{hint}_{self.counter[0]}"


def _convert_node(node, ins, ctx):
    """Returns the ONNX output name for `node` (appends NodeProto(s))."""
    g = ctx.graph
    op = node.op
    attrs = {k: v for k, v in node.attrs.items() if not k.startswith("_")}
    out = node.name

    def emit(op_type, inputs, outputs=None, **oattrs):
        n = g.node.add()
        n.op_type = op_type
        n.name = out
        n.input.extend(inputs)
        n.output.extend(outputs or [out])
        for k, v in oattrs.items():
            _attr(n, k, v)
        return (outputs or [out])[0]

    if op == "Convolution":
        spatial = 2
        kw = {"kernel_shape": _pair(attrs.get("kernel"), spatial),
              "strides": _pair(attrs.get("stride", 1), spatial),
              "dilations": _pair(attrs.get("dilate", 1), spatial),
              "group": int(attrs.get("num_group", 1))}
        pads = _pair(attrs.get("pad", 0), spatial)
        kw["pads"] = pads + pads
        return emit("Conv", ins, **kw)
    if op == "Deconvolution":
        spatial = 2
        kw = {"kernel_shape": _pair(attrs.get("kernel"), spatial),
              "strides": _pair(attrs.get("stride", 1), spatial),
              "group": int(attrs.get("num_group", 1))}
        pads = _pair(attrs.get("pad", 0), spatial)
        kw["pads"] = pads + pads
        return emit("ConvTranspose", ins, **kw)
    if op == "FullyConnected":
        no_bias = bool(attrs.get("no_bias", False)) or len(ins) < 3
        data = ins[0]
        if attrs.get("flatten", True):
            data = emit("Flatten", [ins[0]],
                        outputs=[ctx.fresh(out + "_flat")], axis=1)
        gemm_in = [data, ins[1]]
        if not no_bias:
            gemm_in.append(ins[2])
        else:
            zname = out + "_zero_bias"
            nh = int(attrs.get("num_hidden"))
            ctx.extra_init[zname] = _np.zeros(nh, _np.float32)
            gemm_in.append(zname)
        n = g.node.add()
        n.op_type = "Gemm"
        n.name = out
        n.input.extend(gemm_in)
        n.output.append(out)
        _attr(n, "transB", 1)
        return out
    if op == "Activation":
        act = attrs.get("act_type", "relu")
        if act not in _ACT:
            raise MXNetError(f"onnx export: Activation {act}")
        return emit(_ACT[act], ins)
    if op == "LeakyReLU":
        act = attrs.get("act_type", "leaky")
        if act == "leaky":
            return emit("LeakyRelu", ins,
                        alpha=float(attrs.get("slope", 0.25)))
        if act == "elu":
            return emit("Elu", ins, alpha=float(attrs.get("slope", 0.25)))
        if act == "prelu":
            return emit("PRelu", ins)
        raise MXNetError(f"onnx export: LeakyReLU {act}")
    if op == "BatchNorm":
        return emit("BatchNormalization", ins[:5],
                    epsilon=float(attrs.get("eps", 1e-5)),
                    momentum=float(attrs.get("momentum", 0.9)))
    if op == "Pooling":
        pt = attrs.get("pool_type", "max")
        if attrs.get("global_pool", False):
            return emit("GlobalMaxPool" if pt == "max"
                        else "GlobalAveragePool", ins)
        spatial = 2
        kw = {"kernel_shape": _pair(attrs.get("kernel"), spatial),
              "strides": _pair(attrs.get("stride", 1), spatial)}
        pads = _pair(attrs.get("pad", 0), spatial)
        kw["pads"] = pads + pads
        if pt == "avg":
            kw["count_include_pad"] = int(attrs.get("count_include_pad",
                                                    True))
        return emit("MaxPool" if pt == "max" else "AveragePool", ins,
                    **kw)
    if op == "Flatten":
        return emit("Flatten", ins, axis=1)
    if op == "Dropout":
        return emit("Dropout", ins, ratio=float(attrs.get("p", 0.5)))
    if op in ("softmax", "log_softmax"):
        ax = int(attrs.get("axis", -1))
        name = emit("Softmax", ins, axis=ax)
        if op == "log_softmax":
            return emit("Log", [name], outputs=[out + "_log"])
        return name
    if op == "Concat":
        return emit("Concat", ins,
                    axis=int(attrs.get("dim", attrs.get("axis", 1))))
    if op == "clip":
        return emit("Clip", ins[:1],
                    min=float(attrs.get("a_min",
                                        _scalar_value_or(node, 1, -3.4e38))),
                    max=float(attrs.get("a_max",
                                        _scalar_value_or(node, 2, 3.4e38))))
    if op == "Reshape":
        shape = attrs.get("shape")
        sname = out + "_shape"
        ctx.extra_init[sname] = _np.asarray(shape, _np.int64)
        return emit("Reshape", [ins[0], sname])
    if op == "Embedding":
        # ONNX Gather(weight, indices)
        return emit("Gather", [ins[1], ins[0]], axis=0)
    if op == "transpose":
        return emit("Transpose", ins,
                    perm=[int(v) for v in attrs.get("axes", ())])
    if op == "expand_dims":
        return emit("Unsqueeze", ins, axes=[int(attrs.get("axis", 0))])
    if op in _REDUCE:
        ax = attrs.get("axis")
        kw = {"keepdims": int(attrs.get("keepdims", False))}
        if ax is not None:
            kw["axes"] = [ax] if isinstance(ax, int) else list(ax)
        return emit(_REDUCE[op], ins, **kw)
    if op in _ELEM:
        return emit(_ELEM[op], ins[:2])
    if op in _UNARY:
        return emit(_UNARY[op], ins)
    raise MXNetError(
        f"onnx export: op '{op}' has no ONNX mapping (supported: conv "
        "family, FC, norm, pool, activations, elemwise, reduce, reshape)")


def _scalar_value_or(node, idx, default):
    if len(node.inputs) > idx:
        v = _scalar_value(node.inputs[idx])
        if v is not None:
            return v
    return default


def get_model_proto(sym, params, input_shape, input_type="float32",
                    input_names=("data",)):
    """Build a ModelProto from a Symbol + param dict."""
    from ...ndarray.ndarray import NDArray

    model = _pb.ModelProto()
    model.ir_version = 4
    model.producer_name = "mxnet_tpu"
    ops = model.opset_import.add()
    ops.domain = ""
    ops.version = _OPSET
    g = model.graph
    g.name = getattr(sym, "name", "mxnet_tpu_graph")

    params = {k.split(":", 1)[-1]: v for k, v in (params or {}).items()}
    raw = {k: (v.asnumpy() if isinstance(v, NDArray) else _np.asarray(v))
           for k, v in params.items()}

    ctx = _Ctx(g, raw)
    topo = sym._topo()
    names = {}
    input_names = ([input_names] if isinstance(input_names, str)
                   else list(input_names))

    for node in topo:
        if node.op is None:
            if "__scalar__" in node.attrs or node.attrs.get("__null__"):
                names[id(node)] = None  # resolved by consumers
            else:
                names[id(node)] = node.name
            continue
        ins = []
        for i in node.inputs:
            nm = names[id(i)]
            ins.append(nm)
        ins = [i for i in ins if i is not None]
        names[id(node)] = _convert_node(node, ins, ctx)

    # graph inputs: data + every free variable not in params
    shapes = (input_shape if isinstance(input_shape, list)
              else [input_shape])
    for nm, shp in zip(input_names, shapes):
        vi = g.input.add()
        vi.name = nm
        vi.type.tensor_type.elem_type = _DT[input_type]
        for d in shp:
            vi.type.tensor_type.shape.dim.add().dim_value = int(d)
    for nm, arr in raw.items():
        g.initializer.append(_tensor(nm, arr))
        vi = g.input.add()
        vi.name = nm
        vi.type.tensor_type.elem_type = _DT.get(str(arr.dtype),
                                                _pb.TensorProto.FLOAT)
        for d in arr.shape:
            vi.type.tensor_type.shape.dim.add().dim_value = int(d)
    for nm, arr in ctx.extra_init.items():
        g.initializer.append(_tensor(nm, arr))
        vi = g.input.add()
        vi.name = nm
        vi.type.tensor_type.elem_type = _DT[str(arr.dtype)]
        for d in arr.shape:
            vi.type.tensor_type.shape.dim.add().dim_value = int(d)

    vo = g.output.add()
    vo.name = names[id(topo[-1])] if topo else ""
    vo.type.tensor_type.elem_type = _DT[input_type]
    return model


def export_model(sym, params, input_shape, input_type="float32",
                 onnx_file_path="model.onnx", verbose=False,
                 input_names=("data",)):
    """Reference signature: onnx_mxnet.export_model.  `sym` may be a
    Symbol or a path to a -symbol.json; `params` a dict or .params
    path."""
    from ... import symbol as _sym_mod
    from ...ndarray import load as nd_load

    if isinstance(sym, str):
        sym = _sym_mod.load(sym)
    if isinstance(params, str):
        params = nd_load(params)
    model = get_model_proto(sym, params, input_shape, input_type,
                            input_names)
    with open(onnx_file_path, "wb") as f:
        f.write(model.SerializeToString())
    return onnx_file_path
