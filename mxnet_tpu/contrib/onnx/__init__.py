"""ONNX interop (reference: python/mxnet/contrib/onnx — mx2onnx
export_model + onnx2mx import_model).

The environment ships no `onnx` package, so the wire format is handled
directly: `onnx_minimal.proto` is a faithful subset of the public ONNX
schema (same field numbers), compiled with protoc into
`onnx_minimal_pb2`.  Files produced here are standard .onnx protobufs
readable by onnxruntime/netron; files read here must use the ops in the
support table (the model-zoo CNN family).
"""

from .mx2onnx import export_model, get_model_proto
from .onnx2mx import import_model, import_to_gluon
