"""Checkpointing.

Reference parity: SURVEY §5.4 — three surfaces: (1) the NDArray container
format (ndarray/utils.py save/load — byte-compatible with `.params`),
(2) gluon save/load_parameters + export, (3) Module save_checkpoint.

This module adds the TPU-native fourth surface the reference lacks:
**sharded multi-host checkpoints** via orbax/tensorstore — each host writes
its parameter shards; restore re-lays arrays onto the (possibly different)
mesh; async snapshotting overlaps training (preemption-aware: checkpoint on
SIGTERM; checkpoint-restart is the recovery primitive, SURVEY §5.3).
"""

from __future__ import annotations

import os
import signal
import threading

from .base import MXNetError


class ShardedCheckpointer:
    """Save/restore sharded train state (params + optimizer + step).

    Works with parallel.ShardedTrainer or any pytree of jax arrays.
    """

    def __init__(self, directory, max_to_keep=3, async_save=True):
        import orbax.checkpoint as ocp

        self._dir = os.path.abspath(directory)
        os.makedirs(self._dir, exist_ok=True)
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep, enable_async_checkpointing=async_save)
        self._mgr = ocp.CheckpointManager(self._dir, options=options)

    def save(self, step, state):
        """state: pytree of jax arrays (sharded arrays write only local
        shards per host)."""
        import orbax.checkpoint as ocp

        self._mgr.save(step, args=ocp.args.StandardSave(state))
        return step

    def restore(self, step=None, template=None):
        """Restore the given (or latest) step; `template` (a pytree of
        arrays or ShapeDtypeStruct+sharding) re-lays shards on the current
        mesh."""
        import orbax.checkpoint as ocp

        if step is None:
            step = self._mgr.latest_step()
            if step is None:
                raise MXNetError(f"no checkpoints under {self._dir}")
        if template is not None:
            return self._mgr.restore(
                step, args=ocp.args.StandardRestore(template))
        return self._mgr.restore(step)

    def latest_step(self):
        return self._mgr.latest_step()

    def all_steps(self):
        """All retained checkpoint steps, ascending (resilience.py walks
        these newest-first when the latest is corrupt/partial)."""
        return sorted(self._mgr.all_steps())

    def wait(self):
        """Block until async saves finish."""
        self._mgr.wait_until_finished()

    def close(self):
        self._mgr.close()


def trainer_state(trainer):
    """Extract a ShardedTrainer's full state as a pytree."""
    return {
        "params": list(trainer._param_vals),
        "opt_state": [list(s) for s in trainer._opt_state],
        "aux": dict(trainer._aux_vals),
        "num_update": trainer._num_update,
    }


def load_trainer_state(trainer, state):
    """Load a restored pytree back into a ShardedTrainer."""
    import jax

    trainer._param_vals = [
        jax.device_put(v, s) for v, s in
        zip(state["params"], trainer._param_shardings)]
    trainer._opt_state = [
        tuple(jax.device_put(x, sh) for x in st)
        for st, sh in zip(state["opt_state"], trainer._param_shardings)]
    from jax.sharding import NamedSharding, PartitionSpec

    repl = NamedSharding(trainer.mesh, PartitionSpec())
    trainer._aux_vals = {k: jax.device_put(v, repl)
                         for k, v in state["aux"].items()}
    trainer._num_update = int(state["num_update"])
    trainer.sync_params()
    return trainer


class PreemptionHandler:
    """Checkpoint on SIGTERM (TPU preemption notice).  Reference story is
    'restart from the last epoch checkpoint' (SURVEY §5.3); on TPU we get
    a grace window — snapshot mid-epoch state and exit cleanly.

    Usable as a context manager (``with PreemptionHandler(...):``), and
    chains to any previously-installed SIGTERM handler so stacking with
    an outer supervisor (e.g. a launcher's own grace logic) keeps both
    alive."""

    def __init__(self, checkpointer, get_state, get_step):
        self._ckpt = checkpointer
        self._get_state = get_state
        self._get_step = get_step
        self.preempted = threading.Event()
        self._restored = False
        self._prev = signal.signal(signal.SIGTERM, self._on_sigterm)

    def _on_sigterm(self, signum, frame):
        self.preempted.set()
        # chain: a previously-installed python handler still runs (the
        # reference bug was dropping it — an outer supervisor's grace
        # logic silently disabled)
        if callable(self._prev):
            self._prev(signum, frame)

    def maybe_checkpoint(self):
        """Call at step boundaries; saves + returns True when preempted."""
        if not self.preempted.is_set():
            return False
        self._ckpt.save(self._get_step(), self._get_state())
        self._ckpt.wait()
        return True

    def restore_handler(self):
        if self._restored:
            return
        # signal.signal rejects None (getsignal returns None for handlers
        # not installed from python) — fall back to the default action
        signal.signal(signal.SIGTERM,
                      self._prev if self._prev is not None
                      else signal.SIG_DFL)
        self._restored = True

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.restore_handler()
        return False
