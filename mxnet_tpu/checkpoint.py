"""Checkpointing.

Reference parity: SURVEY §5.4 — three surfaces: (1) the NDArray container
format (ndarray/utils.py save/load — byte-compatible with `.params`),
(2) gluon save/load_parameters + export, (3) Module save_checkpoint.

This module adds the TPU-native fourth surface the reference lacks:
**sharded multi-host checkpoints**.  The native engine is
:class:`AsyncCheckpointer` — `resilience.LocalCheckpointer`'s multi-host
big sibling, no orbax required:

- **async saves**: ``save()`` takes a consistent copy-on-snapshot of the
  state pytree (device→host before returning, so donated/mutated buffers
  are never read later) and a background writer serializes/fsyncs off
  the critical path, with exactly-one-outstanding-save backpressure and
  error propagation into the next ``save()``/``wait()``.
- **two-phase multi-host commit**: each rank writes its local shards
  (per-shard CRC) plus a rank-local manifest entry, barriers, then
  rank 0 atomically renames the global ``MANIFEST.json`` — the single
  commit point.  A crash at ANY instant leaves either the previous or
  the new checkpoint fully restorable; orphan shards are garbage
  collected on the next save.
- **elastic restore**: a checkpoint written by N hosts restores onto M
  hosts or a different mesh via a ``template`` pytree of shardings, with
  hard validation errors for shape/dtype/world-size mismatches.

`ShardedCheckpointer` (orbax/tensorstore) remains as an opt-in backend;
`make_checkpointer` picks the right engine.  Preemption-aware throughout
(checkpoint on SIGTERM; checkpoint-restart is the recovery primitive,
SURVEY §5.3).
"""

from __future__ import annotations

import copy
import json
import os
import pickle
import shutil
import signal
import socket
import struct
import sys
import threading
import time
import zlib

from . import integrity
from . import resilience
from . import telemetry
from .base import MXNetError
from .resilience import CheckpointCorrupt


# -- pytree plumbing (jax-free: dict / list / tuple / scalars / arrays) --------

_MANIFEST_MAGIC = "MXTMANIFEST1"
_MANIFEST_VERSION = 1
_SHARD_MAGIC = b"MXTCKPT1"          # same framing as LocalCheckpointer
_SCALARS = (int, float, bool, str, bytes, type(None))


def _is_array(v):
    return hasattr(v, "__array__")


def snapshot_to_host(state):
    """Deep copy-on-snapshot: every array leaf becomes a HOST numpy copy.

    Called synchronously inside ``save()`` so that (a) donated device
    buffers — invalidated by the very next compiled step — are never
    read by the background writer, and (b) a trainer mutating its
    weights in place can't race the serialization.  ``np.asarray`` on a
    device array already copies to host; a numpy leaf is copied
    explicitly (``np.asarray`` would alias it).
    """
    import numpy as np

    def conv(v):
        if isinstance(v, dict):
            return {k: conv(x) for k, x in v.items()}
        if isinstance(v, (list, tuple)):
            out = [conv(x) for x in v]
            return out if isinstance(v, list) else tuple(out)
        if isinstance(v, np.ndarray):
            return np.array(v, copy=True)
        if _is_array(v):
            return np.asarray(getattr(v, "_data", v))
        return v

    return conv(state)


def _flatten(state):
    """Flatten a pytree into (leaves, skeleton): array leaves become
    ``{"__leaf__": i}`` markers, scalars inline, containers stay JSON —
    so the skeleton travels inside MANIFEST.json and restore needs no
    pickled structure."""
    leaves = []

    def walk(v):
        if isinstance(v, dict):
            for k in v:
                if not isinstance(k, str):
                    raise MXNetError(
                        f"checkpoint state dict keys must be str, got "
                        f"{type(k).__name__} ({k!r})")
            return {k: walk(x) for k, x in v.items()}
        if isinstance(v, tuple):
            return {"__tuple__": [walk(x) for x in v]}
        if isinstance(v, list):
            return [walk(x) for x in v]
        if _is_array(v):
            leaves.append(v)
            return {"__leaf__": len(leaves) - 1}
        if isinstance(v, _SCALARS):
            return {"__scalar__": v}
        raise MXNetError(
            f"checkpoint state contains an unserializable leaf of type "
            f"{type(v).__name__}")

    return leaves, walk(state)


def _unflatten(skeleton, leaves):
    """Rebuild the pytree from a manifest skeleton + leaf mapping."""
    def walk(s):
        if isinstance(s, dict):
            if "__leaf__" in s:
                return leaves[s["__leaf__"]]
            if "__tuple__" in s:
                return tuple(walk(x) for x in s["__tuple__"])
            if "__scalar__" in s:
                return s["__scalar__"]
            return {k: walk(x) for k, x in s.items()}
        if isinstance(s, list):
            return [walk(x) for x in s]
        raise CheckpointCorrupt(f"manifest skeleton node {s!r} invalid")

    return walk(skeleton)


def _write_shard(path, payload_by_leaf):
    """Write one rank's shard — ``MXTCKPT1 | crc32 | length | pickle`` —
    durably (fsync file, then the directory).  Returns (crc, size).

    The ``crash_during_save`` fault site kills the process after HALF
    the payload hits disk: the torn file is exactly what a real power
    cut leaves, and the commit protocol must shrug it off.
    """
    blob = pickle.dumps(payload_by_leaf, protocol=4)
    crc = zlib.crc32(blob) & 0xffffffff
    header = _SHARD_MAGIC + struct.pack("<IQ", crc, len(blob))
    tmp = path + ".tmp"
    half = len(blob) // 2
    with open(tmp, "wb") as f:
        f.write(header)
        f.write(blob[:half])
        f.flush()   # the torn-write point: half the payload is on disk
        resilience.maybe_crash("crash_during_save")
        f.write(blob[half:])
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    resilience.fsync_dir(os.path.dirname(path))
    return crc, len(blob)


def _read_shard(path, crc=None, size=None):
    """Read + CRC-validate one shard file (io_retry: flaky-NFS class)."""
    def read():
        with open(path, "rb") as f:
            return f.read()

    blob = resilience.io_retry(read, description=f"read {path}")
    hdr = len(_SHARD_MAGIC) + 12
    if len(blob) < hdr or not blob.startswith(_SHARD_MAGIC):
        raise CheckpointCorrupt(f"{path}: bad shard magic")
    fcrc, flen = struct.unpack("<IQ", blob[len(_SHARD_MAGIC):hdr])
    payload = blob[hdr:]
    if len(payload) != flen or (size is not None and flen != size):
        raise CheckpointCorrupt(
            f"{path}: truncated (want {size if size is not None else flen}"
            f" payload bytes, have {len(payload)})")
    actual = zlib.crc32(payload) & 0xffffffff
    if actual != fcrc or (crc is not None and actual != crc):
        raise CheckpointCorrupt(f"{path}: checksum mismatch")
    return pickle.loads(payload)


class ShardedCheckpointer:
    """Save/restore sharded train state (params + optimizer + step).

    Works with parallel.ShardedTrainer or any pytree of jax arrays.
    """

    def __init__(self, directory, max_to_keep=3, async_save=True):
        import orbax.checkpoint as ocp

        self._dir = os.path.abspath(directory)
        os.makedirs(self._dir, exist_ok=True)
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep, enable_async_checkpointing=async_save)
        self._mgr = ocp.CheckpointManager(self._dir, options=options)

    def save(self, step, state):
        """state: pytree of jax arrays (sharded arrays write only local
        shards per host)."""
        import orbax.checkpoint as ocp

        self._mgr.save(step, args=ocp.args.StandardSave(state))
        return step

    def restore(self, step=None, template=None):
        """Restore the given (or latest) step; `template` (a pytree of
        arrays or ShapeDtypeStruct+sharding) re-lays shards on the current
        mesh."""
        import orbax.checkpoint as ocp

        if step is None:
            step = self._mgr.latest_step()
            if step is None:
                raise MXNetError(f"no checkpoints under {self._dir}")
        if template is not None:
            return self._mgr.restore(
                step, args=ocp.args.StandardRestore(template))
        return self._mgr.restore(step)

    def latest_step(self):
        return self._mgr.latest_step()

    def all_steps(self):
        """All retained checkpoint steps, ascending (resilience.py walks
        these newest-first when the latest is corrupt/partial)."""
        return sorted(self._mgr.all_steps())

    def wait(self):
        """Block until async saves finish."""
        self._mgr.wait_until_finished()

    def close(self):
        self._mgr.close()


# -- native async multi-host engine --------------------------------------------

def _dist_info():
    """(rank, world_size) of the current process — (0, 1) when jax (or
    the distributed runtime) is unavailable."""
    try:
        import jax

        return jax.process_index(), jax.process_count()
    except Exception:
        return 0, 1


class AsyncCheckpointer:
    """Native async snapshot-and-commit checkpoints, single- or multi-host.

    Layout (one directory per step, shared storage across hosts)::

        <dir>/step_0000000120/shard_00000.mxtckpt   rank 0's leaves
                              shard_00001.mxtckpt   rank 1's leaves
                              rank_00000.json       per-rank manifest entry
                              rank_00001.json
                              MANIFEST.json         THE commit point

    Leaves of the flattened state pytree are partitioned round-robin
    across ranks (``leaf_index % world_size``); each rank host-copies
    and writes only its own slice, so snapshot cost and write bandwidth
    scale down with the fleet.  ``MANIFEST.json`` (magic, world size,
    step, skeleton, per-shard CRCs/sizes) is written by rank 0 with
    tmp-file + ``os.replace`` + directory fsync AFTER a cross-host
    barrier confirms every shard is durable: a crash at any instant
    leaves either the previous or the new checkpoint fully restorable,
    never a torn one.  Restore reassembles from the manifest and — via a
    ``template`` pytree of shardings — re-lays the state onto any world
    size or mesh.

    Same save/restore/latest_step/all_steps/wait surface as
    `resilience.LocalCheckpointer`, so `resilience.run_resilient`,
    `DivergenceMonitor` rollback, and `PreemptionHandler` compose with
    it unchanged.
    """

    def __init__(self, directory, max_to_keep=3, async_save=None,
                 rank=None, world_size=None, logger=None,
                 barrier_fn=None):
        self._dir = os.path.abspath(directory)
        os.makedirs(self._dir, exist_ok=True)
        self.max_to_keep = max_to_keep
        if async_save is None:
            async_save = os.environ.get(
                "MXTPU_ASYNC_CKPT", "1").lower() not in ("0", "false",
                                                         "off")
        self.async_save = bool(async_save)
        if rank is None or world_size is None:
            r, w = _dist_info()
            # a cross-host barrier only exists when the world size came
            # from the real distributed runtime (tests fake N ranks in
            # one process by passing rank=/world_size= explicitly)
            self._use_barrier = world_size is None and w > 1
            rank = r if rank is None else rank
            world_size = w if world_size is None else world_size
        else:
            self._use_barrier = False
        self.rank = int(rank)
        self.world_size = int(world_size)
        # explicit rank/world gangs (no jax distributed runtime) can
        # still sync the two-phase commit through a caller-supplied
        # barrier — ElasticGang.barrier, which stays death-responsive
        self._barrier_fn = barrier_fn
        self._logger = logger
        self._thread = None
        self._pending_step = None
        self._error = None
        self._lock = threading.Lock()
        # peer RAM replication (attach_peers): ship each save's shard
        # dict to the buddy rank every N saves
        self._peer_store = None
        self._peer_every = 0
        # epoch fencing (attach_gang): stamp the gang epoch into shard
        # votes and MANIFEST.json, and re-validate it right before the
        # atomic manifest rename
        self._gang_epoch_fn = None
        self._gang_fence_fn = None

    # -- paths -----------------------------------------------------------------

    def _step_dir(self, step):
        return os.path.join(self._dir, f"step_{int(step):010d}")

    @staticmethod
    def _shard_name(rank):
        return f"shard_{rank:05d}.mxtckpt"

    @staticmethod
    def _entry_name(rank):
        return f"rank_{rank:05d}.json"

    # -- save ------------------------------------------------------------------

    def save(self, step, state, data_state=None):
        """Snapshot ``state`` to host and return; serialization, fsync,
        and the cross-host commit run on a background writer (unless
        ``async_save=False``).  At most ONE save is outstanding: a new
        ``save()`` first blocks on the previous commit (backpressure),
        and any error the writer hit is raised here or in ``wait()``.

        ``data_state`` (optional): a JSON-serializable input-pipeline
        ``state_dict()`` (see gluon/data/state.py).  Captured here,
        synchronously — the pipeline keeps advancing while the writer
        runs — and stamped into MANIFEST.json with a CRC so restore
        resumes at the exact sample offset."""
        # everything before save() returns — backpressure join, host
        # snapshot, sync commit — stalls the train loop; the async
        # writer's work after that does not
        t0 = time.perf_counter()
        self._join(raise_error=True)
        leaves, skeleton = _flatten(state)
        mine, metas = self._snapshot_local(leaves)
        ds = None if data_state is None else copy.deepcopy(data_state)
        if not self.async_save:
            with resilience.guard_checkpoint(f"ckpt_save:{step}"):
                self._commit(step, mine, metas, skeleton, ds)
            self._count_stall(t0)
            return step
        self._pending_step = step
        self._thread = threading.Thread(
            target=self._writer, args=(step, mine, metas, skeleton, ds),
            name=f"ckpt_writer:{step}", daemon=True)
        self._thread.start()
        self._count_stall(t0)
        return step

    @staticmethod
    def _count_stall(t0):
        telemetry.count("ckpt.stall_us",
                        int((time.perf_counter() - t0) * 1e6))
        telemetry.count("ckpt.saves")

    def _snapshot_local(self, leaves):
        """Host-copy THIS rank's leaves; record every leaf's meta.

        The copy happens here, synchronously, before ``save()`` returns:
        device buffers may be donated to (and invalidated by) the very
        next compiled step, and numpy state may be mutated in place by
        the trainer — the writer thread must never touch the originals.
        """
        import numpy as np

        mine, metas = {}, {}
        for i, v in enumerate(leaves):
            arr = getattr(v, "_data", v)
            metas[i] = {"shape": list(np.shape(arr)),
                        "dtype": str(getattr(arr, "dtype", "object")),
                        "shard": i % self.world_size}
            if i % self.world_size == self.rank:
                mine[i] = np.array(arr, copy=True) \
                    if isinstance(arr, np.ndarray) else np.asarray(arr)
        return mine, metas

    def _writer(self, step, mine, metas, skeleton, data_state=None):
        timeout = os.environ.get("MXTPU_CKPT_TIMEOUT")
        # dump-only watchdog: a hung filesystem in the WRITER thread
        # surfaces as stack dumps now and an error at the train thread's
        # next save()/wait() (which guard_checkpoint supervises)
        wd = resilience.Watchdog(
            float(timeout), name=f"async_ckpt:{step}",
            action="none").start() if timeout else None
        try:
            self._commit(step, mine, metas, skeleton, data_state)
        except BaseException as e:          # noqa: BLE001
            with self._lock:
                self._error = e
        finally:
            if wd is not None:
                wd.cancel()

    def _commit(self, step, mine, metas, skeleton, data_state=None):
        """Phase 1: durable local shard + rank entry.  Barrier.
        Phase 2: rank 0 atomically renames MANIFEST.json."""
        sdir = self._step_dir(step)
        if self.rank == 0:
            self._gc_orphans(keep_step=step)
        os.makedirs(sdir, exist_ok=True)
        crc, size = _write_shard(
            os.path.join(sdir, self._shard_name(self.rank)), mine)
        entry = {"rank": self.rank, "file": self._shard_name(self.rank),
                 "crc": crc, "size": size,
                 "leaves": sorted(mine),
                 "leaf_meta": {str(i): metas[i] for i in metas}}
        if self._gang_epoch_fn is not None:
            # the shard vote carries the epoch it was written under —
            # rank 0's fence check and post-hoc audits read it back
            entry["gang_epoch"] = int(self._gang_epoch_fn())
        epath = os.path.join(sdir, self._entry_name(self.rank))
        with open(epath + ".tmp", "w") as f:
            json.dump(entry, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(epath + ".tmp", epath)
        resilience.fsync_dir(sdir)
        if self._peer_store is not None and self._peer_every and \
                int(step) % self._peer_every == 0:
            # peer RAM replica rides the writer thread: the host shard
            # copy already exists, so the extra cost is one pickle+send
            buddy = (self.rank + 1) % self.world_size
            payload = mine if data_state is None else \
                _peer_wrap(mine, data_state)
            self._peer_store.hold_own(step, payload)
            if buddy != self.rank:
                self._peer_store.send_to(buddy, step, payload)
        self._barrier(f"ckpt_shards_{step}")
        resilience.maybe_crash("crash_before_manifest")
        if self.rank == 0:
            self._write_manifest(step, sdir, skeleton, data_state)
            self._corrupt_shard_fault(sdir)
        self._barrier(f"ckpt_commit_{step}")
        if self.rank == 0:
            self._prune()
        self._log(f"checkpoint step {step} committed "
                  f"(rank {self.rank}/{self.world_size})")
        telemetry.count("ckpt.commits")
        telemetry.event("ckpt_commit", step=int(step), rank=self.rank)

    def _barrier(self, name):
        if self._barrier_fn is not None:
            self._barrier_fn(name)
        elif self._use_barrier:
            from . import distributed

            distributed.barrier(name)

    def attach_peers(self, store, every=None):
        """Enable peer RAM replication: every ``every`` saves (default
        ``MXTPU_PEER_SNAP_EVERY``, 10) the writer ships this rank's CRC'd
        shard dict to buddy ``(rank+1) % world`` via ``store`` (a
        :class:`PeerSnapshotStore`) and keeps its own RAM copy — the
        fast elastic-recovery source that spares the disk manifest."""
        self._peer_store = store
        self._peer_every = int(
            os.environ.get("MXTPU_PEER_SNAP_EVERY", 10)
            if every is None else every)
        return self

    def attach_gang(self, epoch_fn, fence_fn=None):
        """Enable epoch fencing on the durable commit (schema v8).

        ``epoch_fn()`` returns the gang epoch THIS rank believes it is
        in — stamped into its rank entry and into MANIFEST.json.
        ``fence_fn()`` returns the highest COMMITTED epoch (the KV
        fence); rank 0 re-validates its own epoch against it
        immediately before the atomic manifest rename and ABORTS the
        commit when a newer epoch has committed meanwhile — a paused
        or partitioned rank 0 must not publish a stale restore point
        (``ckpt_fenced`` event, no orphan manifest, the previous
        manifest stays the restore point).  An unreachable KV fails
        closed: no fence answer, no rename."""
        self._gang_epoch_fn = epoch_fn
        self._gang_fence_fn = fence_fn
        return self

    def _write_manifest(self, step, sdir, skeleton, data_state=None):
        shards, leaf_meta = [], {}
        for r in range(self.world_size):
            epath = os.path.join(sdir, self._entry_name(r))

            def read(p=epath):
                with open(p) as f:
                    return json.load(f)

            try:
                entry = resilience.io_retry(
                    read, description=f"read {epath}")
            except FileNotFoundError:
                raise MXNetError(
                    f"checkpoint step {step}: rank {r} wrote no manifest "
                    f"entry after the shard barrier — commit aborted "
                    f"(previous checkpoint remains valid)") from None
            shards.append({"file": entry["file"], "rank": entry["rank"],
                           "crc": entry["crc"], "size": entry["size"],
                           "leaves": entry["leaves"]})
            leaf_meta.update(entry["leaf_meta"])
        manifest = {"magic": _MANIFEST_MAGIC,
                    "version": _MANIFEST_VERSION,
                    "step": int(step),
                    "world_size": self.world_size,
                    "skeleton": skeleton,
                    "leaf_meta": leaf_meta,
                    "shards": shards}
        stamp = integrity.manifest_stamp()
        if stamp is not None:
            # tier-3 provenance: the attestation-ledger head at commit
            # time — restore audits it back to the chain (optional key,
            # same manifest version: old readers ignore it)
            manifest["integrity"] = stamp
        if data_state is not None:
            # input-pipeline resume point (optional key, same manifest
            # version: manifests without it restore exactly as before)
            manifest["data_state"] = resilience.data_state_stamp(
                data_state)
        if self._gang_epoch_fn is not None:
            manifest["gang_epoch"] = int(self._gang_epoch_fn())
        mpath = os.path.join(sdir, "MANIFEST.json")
        with open(mpath + ".tmp", "w") as f:
            json.dump(manifest, f)
            f.flush()
            os.fsync(f.fileno())
        # fence re-validation IMMEDIATELY before the atomic rename: all
        # the durable work above is harmless (a .tmp is invisible to
        # restore); the rename is the one operation that publishes a
        # restore point, so it is the one operation a stale rank 0 —
        # resumed from a pause, or the minority side of a partition —
        # must never perform
        self._check_manifest_fence(step, manifest.get("gang_epoch"),
                                   mpath)
        os.replace(mpath + ".tmp", mpath)   # THE commit point
        resilience.fsync_dir(sdir)
        resilience.fsync_dir(self._dir)

    def _check_manifest_fence(self, step, epoch, mpath):
        if self._gang_fence_fn is None or epoch is None:
            return
        try:
            committed = int(self._gang_fence_fn())
            if committed <= int(epoch):
                return
            reason = f"committed gang epoch {committed} > " \
                     f"this rank's epoch {epoch}"
        except Exception as e:      # noqa: BLE001 — fail CLOSED: a
            committed = -1          # rank that cannot read the fence
            reason = f"gang KV unreachable ({e})"   # must not publish
        try:
            os.unlink(mpath + ".tmp")
        except OSError:
            pass
        telemetry.count("ckpt.fenced_aborts")
        telemetry.event("ckpt_fenced", step=int(step), rank=self.rank,
                        epoch=int(epoch), committed=committed,
                        reason=reason[:200])
        raise MXNetError(
            f"checkpoint step {step}: manifest commit FENCED — "
            f"{reason}; the previous manifest remains the restore "
            f"point")

    def _corrupt_shard_fault(self, sdir):
        """``corrupt_shard:K``: bit-rot shard K of the checkpoint that
        just committed (tests the CRC fail-closed path + fallback)."""
        k = resilience.fault_arg("corrupt_shard")
        if k is None or not resilience.consume_charges(
                "corrupt_shard", on_last=False):
            return
        path = os.path.join(sdir, self._shard_name(int(k)))
        with open(path, "r+b") as f:
            f.seek(-4, os.SEEK_END)
            f.write(b"\xde\xad\xbe\xef")

    def _gc_orphans(self, keep_step):
        """Remove uncommitted step dirs (crash leftovers) and stray tmp
        files.  A dir is an orphan iff it has no MANIFEST.json — i.e. a
        crash happened between shard writes and the commit rename."""
        for name in os.listdir(self._dir):
            path = os.path.join(self._dir, name)
            if name.endswith(".tmp"):
                _remove_quiet(path)
                continue
            if not name.startswith("step_") or not os.path.isdir(path):
                continue
            try:
                s = int(name[5:])
            except ValueError:
                continue
            if s != keep_step and \
                    not os.path.exists(os.path.join(path,
                                                    "MANIFEST.json")):
                self._log(f"garbage-collecting orphan checkpoint {name} "
                          f"(no manifest — crashed save)")
                shutil.rmtree(path, ignore_errors=True)

    def _prune(self):
        if not self.max_to_keep:
            return
        for s in self.all_steps()[:-self.max_to_keep]:
            shutil.rmtree(self._step_dir(s), ignore_errors=True)

    # -- wait / error propagation ----------------------------------------------

    def _join(self, raise_error):
        t = self._thread
        if t is not None and t.is_alive():
            with resilience.guard_checkpoint(
                    f"ckpt_wait:{self._pending_step}"):
                t.join()
        self._thread = None
        self._pending_step = None
        if raise_error:
            with self._lock:
                err, self._error = self._error, None
            if err is not None:
                raise err

    def wait(self):
        """Block until the outstanding save commits; re-raise any error
        the background writer hit."""
        self._join(raise_error=True)

    def in_flight(self):
        """True while a background save has not yet committed."""
        t = self._thread
        return t is not None and t.is_alive()

    @property
    def pending_step(self):
        return self._pending_step if self.in_flight() else None

    # -- restore ---------------------------------------------------------------

    def _manifest(self, step):
        mpath = os.path.join(self._step_dir(step), "MANIFEST.json")

        def read():
            with open(mpath) as f:
                return json.load(f)

        try:
            m = resilience.io_retry(read, description=f"read {mpath}")
        except FileNotFoundError:
            raise CheckpointCorrupt(
                f"{mpath}: no manifest (uncommitted checkpoint)") \
                from None
        except ValueError as e:
            raise CheckpointCorrupt(f"{mpath}: unparseable ({e})") from e
        if not isinstance(m, dict) or m.get("magic") != _MANIFEST_MAGIC:
            raise CheckpointCorrupt(f"{mpath}: bad manifest magic")
        if m.get("version") != _MANIFEST_VERSION:
            raise CheckpointCorrupt(
                f"{mpath}: manifest version {m.get('version')} "
                f"(this build reads {_MANIFEST_VERSION})")
        if len(m.get("shards", [])) != m.get("world_size"):
            raise CheckpointCorrupt(
                f"{mpath}: {len(m.get('shards', []))} shard entries for "
                f"world size {m.get('world_size')}")
        return m

    def restore(self, step=None, template=None):
        """Reassemble the checkpoint from its manifest.

        Without ``template``: returns the host (numpy) pytree — world-
        size independent, except that a RUNNING multi-host job whose
        world size differs from the writer's must pass a template (there
        is no way to re-lay shards onto the new fleet otherwise).  With
        ``template`` — a matching pytree whose array positions hold
        `jax.sharding.Sharding`s, arrays, or `jax.ShapeDtypeStruct`s —
        every leaf is validated (shape/dtype) and ``jax.device_put``
        onto the new layout: the elastic N→M restore path.
        """
        # drain (but don't fail on) an in-flight save: its error stays
        # queued for the next save()/wait(), while restore proceeds from
        # the newest COMMITTED checkpoint
        self._join(raise_error=False)
        if step is None:
            step = self.latest_step()
            if step is None:
                raise MXNetError(f"no checkpoints under {self._dir}")
        with resilience.guard_checkpoint(f"ckpt_restore:{step}"):
            m = self._manifest(step)
            ok, why = integrity.verify_provenance(m)
            if not ok:
                raise CheckpointCorrupt(
                    f"checkpoint step {step}: integrity provenance "
                    f"failed — {why}")
            if template is None and self.world_size > 1 \
                    and self._use_barrier \
                    and m["world_size"] != self.world_size:
                raise MXNetError(
                    f"checkpoint step {step} was written by "
                    f"{m['world_size']} hosts but this job runs "
                    f"{self.world_size}: pass template= (a pytree of "
                    f"shardings) to restore elastically")
            leaves = self._load_leaves(step, m)
            state = _unflatten(m["skeleton"], leaves)
        if template is not None:
            state = _apply_template(state, template)
        telemetry.count("ckpt.disk_restores")
        return state

    def _load_leaves(self, step, m):
        import numpy as np

        sdir = self._step_dir(step)
        leaves = {}
        for sh in m["shards"]:
            payload = _read_shard(os.path.join(sdir, sh["file"]),
                                  crc=sh["crc"], size=sh["size"])
            for i in sh["leaves"]:
                if i not in payload:
                    raise CheckpointCorrupt(
                        f"{sh['file']}: leaf {i} listed in manifest but "
                        f"missing from shard payload")
                leaves[i] = payload[i]
        for key, meta in m["leaf_meta"].items():
            i = int(key)
            if i not in leaves:
                raise CheckpointCorrupt(
                    f"checkpoint step {step}: leaf {i} missing from "
                    f"every shard")
            arr = leaves[i]
            if list(np.shape(arr)) != list(meta["shape"]) or \
                    str(arr.dtype) != meta["dtype"]:
                raise CheckpointCorrupt(
                    f"checkpoint step {step}: leaf {i} is "
                    f"{np.shape(arr)}/{arr.dtype}, manifest says "
                    f"{tuple(meta['shape'])}/{meta['dtype']}")
        return leaves

    def verify(self, step):
        """Re-read manifest + every shard, checksum-validated (the
        verify-after-write hook `resilience._save_verified` calls).
        Returns the validated manifest so callers (the serving reload
        gate) can audit its integrity stamp without a second read."""
        m = self._manifest(step)
        self._load_leaves(step, m)
        return m

    def data_state(self, step=None):
        """The input-pipeline ``state_dict`` stamped into ``step``'s
        manifest (latest committed step when None), or None when the
        checkpoint predates data-state stamping — restore stays backward
        compatible.  A present-but-corrupt stamp raises
        `CheckpointCorrupt` (fail closed: silently resuming at the wrong
        sample offset is the one outcome this subsystem exists to
        prevent).  When the step only exists as a peer-RAM snapshot
        (elastic recovery beat the disk manifest), falls through to this
        rank's own held wrap in the attached `PeerSnapshotStore`."""
        self._join(raise_error=False)
        if step is None:
            step = self.latest_step()
            if step is None:
                return None
        mpath = os.path.join(self._step_dir(step), "MANIFEST.json")
        if not os.path.exists(mpath):
            if self._peer_store is not None:
                return self._peer_store.data_state_at(self.rank, step)
            return None
        m = self._manifest(step)
        return resilience.data_state_unstamp(m.get("data_state"))

    # -- listing ---------------------------------------------------------------

    def all_steps(self):
        """Committed steps only (a dir without MANIFEST.json is a crash
        orphan, invisible to resume)."""
        steps = []
        for name in os.listdir(self._dir):
            if not name.startswith("step_"):
                continue
            try:
                s = int(name[5:])
            except ValueError:
                continue
            if os.path.exists(os.path.join(self._dir, name,
                                           "MANIFEST.json")):
                steps.append(s)
        return sorted(steps)

    def latest_step(self):
        steps = self.all_steps()
        return steps[-1] if steps else None

    def close(self):
        self._join(raise_error=True)

    def _log(self, msg):
        if self._logger is not None:
            self._logger.info(msg)
        else:
            sys.stderr.write(f"[checkpoint] {msg}\n")


def latest_manifest_step(directory):
    """Newest committed step in ``directory`` (a ``step_*`` dir with
    MANIFEST.json), or None.  A cheap directory scan — the serving
    reload poller calls this every MXTPU_SERVE_RELOAD_POLL_MS without
    instantiating an AsyncCheckpointer."""
    directory = os.fspath(directory)
    best = None
    try:
        names = os.listdir(directory)
    except OSError:
        return None
    for name in names:
        if not name.startswith("step_"):
            continue
        try:
            s = int(name[5:])
        except ValueError:
            continue
        if (best is None or s > best) and os.path.exists(
                os.path.join(directory, name, "MANIFEST.json")):
            best = s
    return best


def _remove_quiet(path):
    try:
        os.remove(path)
    except OSError:
        pass


# -- peer-replicated in-memory snapshots (elastic recovery, PR 8) --------------

#: wire magic for the peer snapshot protocol (versioned like _SHARD_MAGIC)
_PEER_MAGIC = b"MXTPSNP1"
#: request header after the magic: cmd u8, from_rank u32, step u64,
#: epoch u32, crc u32, payload_len u64
_PEER_HDR = "<BIQIIQ"
_PEER_PUT, _PEER_GET = 1, 2


_PEER_WRAP_KEY = "__mxt_peer_wrap__"


def _peer_wrap(state, data_state):
    """Bundle a snapshot with its input-pipeline state for peer
    replication.  The wrapper is a plain dict so `snapshot_to_host`
    walks it unchanged; unwrapping is transparent (`_peer_unwrap`), so
    stores holding bare pre-wrap snapshots keep working."""
    return {_PEER_WRAP_KEY: 1, "state": state, "data_state": data_state}


def _peer_unwrap(obj):
    """(state, data_state) from a possibly-wrapped peer payload."""
    if isinstance(obj, dict) and obj.get(_PEER_WRAP_KEY) == 1:
        return obj.get("state"), obj.get("data_state")
    return obj, None


def _recv_exact(conn, n):
    buf = b""
    while len(buf) < n:
        chunk = conn.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer snapshot connection closed "
                                  "mid-frame")
        buf += chunk
    return buf


class PeerSnapshotStore:
    """RAM-resident snapshot replicas + a tiny TCP shard server.

    Each rank runs one store: a daemon thread serves this rank's held
    snapshots (its own, plus the buddy shards peers pushed) over a
    length-prefixed CRC'd frame protocol; ``send_to`` pushes a snapshot
    into a peer's RAM, ``fetch`` pulls one out during elastic recovery.
    Addresses are advertised through the gang KV (``addr/<rank>``), so
    any survivor can locate any holder without a rendezvous.

    Frame: ``MXTPSNP1 | cmd u8 | from_rank u32 | step u64 | epoch u32 |
    crc32 u32 | len u64 | pickle(snapshot_to_host(state))``.  The CRC is
    validated on BOTH ends — a recovery source that silently bit-rots in
    transit is worse than falling back to the disk manifest.

    Retention is ``keep`` snapshot steps per source rank (default 2),
    PLUS anything younger than ``retain_s`` (default 2x the heartbeat
    timeout): between a rank's death and its CONFIRMATION the survivors
    keep stepping and snapshotting, and if count-based pruning could
    drop every step the dead rank's buddy still holds, no common
    restore point would survive the detection window — the time floor
    guarantees one does, with RAM cost bounded by the snapshot cadence
    over that window.
    """

    def __init__(self, rank, kv=None, host=None, keep=2, retain_s=None):
        self.rank = int(rank)
        self.kv = kv
        self.host = host or os.environ.get("MXTPU_PEER_HOST",
                                           "127.0.0.1")
        self.keep = int(keep)
        if retain_s is None:
            retain_s = float(os.environ.get(
                "MXTPU_PEER_SNAP_RETAIN",
                2.0 * float(os.environ.get("MXTPU_HEARTBEAT_TIMEOUT",
                                           5.0))))
        self.retain_s = float(retain_s)
        self.port = None
        self._held = {}        # from_rank -> {step: (epoch, blob)}
        self._fence = 0        # drop PUT frames older than this epoch
        self._lock = threading.Lock()
        self._sock = None
        self._thread = None
        self._stop = threading.Event()

    def fence(self, epoch):
        """Raise the receive fence: PUT frames stamped with a gang
        epoch older than ``epoch`` are acked but NOT stored — a zombie
        sender (paused across a reshape, or the minority side of a
        partition) must not plant stale shards in a live rank's RAM.
        Monotonic: a lower value never lowers the fence."""
        with self._lock:
            self._fence = max(self._fence, int(epoch))

    # -- lifecycle -------------------------------------------------------------

    def start(self):
        if self._sock is not None:
            return self
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((self.host, 0))
        s.listen(8)
        s.settimeout(0.2)
        self._sock = s
        self.port = s.getsockname()[1]
        self._thread = threading.Thread(
            target=self._serve, name=f"peer_snap:{self.rank}",
            daemon=True)
        self._thread.start()
        if self.kv is not None:
            self.kv.put_json(f"addr/{self.rank}",
                             {"host": self.host, "port": self.port})
        return self

    def close(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    # -- server ----------------------------------------------------------------

    def _serve(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            try:
                with conn:
                    conn.settimeout(10.0)
                    self._handle(conn)
            except Exception:       # noqa: BLE001 — a malformed frame
                pass                # must not kill the server thread

    def _handle(self, conn):
        hdr_len = len(_PEER_MAGIC) + struct.calcsize(_PEER_HDR)
        hdr = _recv_exact(conn, hdr_len)
        if not hdr.startswith(_PEER_MAGIC):
            raise CheckpointCorrupt("peer snapshot: bad frame magic")
        cmd, from_rank, step, epoch, crc, nbytes = struct.unpack(
            _PEER_HDR, hdr[len(_PEER_MAGIC):])
        if cmd == _PEER_PUT:
            blob = _recv_exact(conn, nbytes)
            if zlib.crc32(blob) & 0xffffffff != crc:
                raise CheckpointCorrupt(
                    f"peer snapshot from rank {from_rank} step {step}: "
                    f"checksum mismatch in transit")
            with self._lock:
                fence = self._fence
            if int(epoch) < fence:
                # stale sender (zombie / partition minority): ack the
                # frame — the sender is not at fault for trying — but
                # never store it (schema v8 fencing)
                telemetry.count("peer_snap.fenced_drops")
                telemetry.event("fencing_rejected", rank=self.rank,
                                sender=int(from_rank), epoch=int(epoch),
                                committed=fence, kind="peer_frame",
                                step=int(step))
                conn.sendall(b"OK")
                return
            self._store(from_rank, step, epoch, blob)
            telemetry.count("peer_snap.recvs")
            conn.sendall(b"OK")
        elif cmd == _PEER_GET:
            with self._lock:
                held = self._held.get(from_rank, {}).get(step)
            if held is None:
                conn.sendall(struct.pack("<BIQ", 0, 0, 0))
                return
            blob = held[1]
            conn.sendall(struct.pack(
                "<BIQ", 1, zlib.crc32(blob) & 0xffffffff, len(blob)))
            conn.sendall(blob)
        else:
            raise CheckpointCorrupt(f"peer snapshot: unknown cmd {cmd}")

    def _store(self, from_rank, step, epoch, blob):
        now = time.monotonic()
        with self._lock:
            d = self._held.setdefault(int(from_rank), {})
            d[int(step)] = (int(epoch), blob, now)
            while len(d) > self.keep:
                oldest = min(d)
                if now - d[oldest][2] <= self.retain_s:
                    break       # still inside the detection window
                del d[oldest]
            # advertise only the steps from THIS epoch: a pre-reshape
            # snapshot must never be offered as a restore point for the
            # reshaped gang (its shard set matches the old membership)
            steps = sorted(s for s, (e, _, _) in d.items()
                           if e == int(epoch))
        if self.kv is not None:
            self.kv.put_json(f"held/{self.rank}/{int(from_rank)}",
                             {"steps": steps, "epoch": int(epoch)})

    # -- local holds -----------------------------------------------------------

    def hold_own(self, step, state, epoch=0):
        """Keep this rank's own snapshot in RAM (served to peers during
        THEIR recovery, and our own rollback source)."""
        blob = pickle.dumps(snapshot_to_host(state), protocol=4)
        self._store(self.rank, step, epoch, blob)

    def own_at(self, step):
        with self._lock:
            held = self._held.get(self.rank, {}).get(int(step))
        if held is None:
            return None
        return _peer_unwrap(pickle.loads(held[1]))[0]

    def data_state_at(self, from_rank, step):
        """The input-pipeline state riding ``from_rank``'s held snapshot
        at ``step``, or None (bare pre-wrap snapshot / nothing held).
        Every rank stamps the same GLOBAL pipeline state, so a survivor
        reads its own held wrap — no network fetch needed."""
        with self._lock:
            held = self._held.get(int(from_rank), {}).get(int(step))
        if held is None:
            return None
        return _peer_unwrap(pickle.loads(held[1]))[1]

    def held_steps(self, from_rank, epoch=None):
        with self._lock:
            d = self._held.get(int(from_rank), {})
            if epoch is None:
                return sorted(d)
            return sorted(s for s, (e, _, _) in d.items()
                          if e == int(epoch))

    def held_ranks(self):
        """Source ranks this store currently holds shards for."""
        with self._lock:
            return sorted(self._held)

    def forget_rank(self, from_rank):
        """Drop every shard held for a departed rank and withdraw its
        advert — a drained peer's snapshots are dead weight once the
        reshape commits (its shard layout matches the old world)."""
        with self._lock:
            dropped = self._held.pop(int(from_rank), None)
        if dropped and self.kv is not None:
            try:
                self.kv.delete(f"held/{self.rank}/{int(from_rank)}")
            except Exception:   # noqa: BLE001 — advert GC is best-effort
                pass

    def prune_ranks(self, members):
        """Free shards held for ranks no longer in the gang.  Call only
        once every surviving member is past shard assembly (the gang
        does this on its first post-reshape snapshot) — pruning during
        recovery itself races a slower survivor's fetch."""
        keep = set(int(r) for r in members)
        with self._lock:
            gone = [r for r in self._held if r not in keep]
        for r in gone:
            self.forget_rank(r)

    # -- client ----------------------------------------------------------------

    def _addr_of(self, rank):
        if self.kv is None:
            return None
        return self.kv.get_json(f"addr/{rank}")

    def send_to(self, peer_rank, step, state, epoch=0, timeout=5.0):
        """Push a snapshot into ``peer_rank``'s RAM.  Best-effort: a
        busy/restarting buddy costs this snapshot its replica, never the
        training step — returns False instead of raising."""
        addr = self._addr_of(peer_rank)
        if not addr:
            return False
        blob = pickle.dumps(snapshot_to_host(state), protocol=4)
        frame = _PEER_MAGIC + struct.pack(
            _PEER_HDR, _PEER_PUT, self.rank, int(step), int(epoch),
            zlib.crc32(blob) & 0xffffffff, len(blob))
        try:
            with socket.create_connection(
                    (addr["host"], addr["port"]), timeout=timeout) as c:
                c.sendall(frame)
                c.sendall(blob)
                ok = _recv_exact(c, 2) == b"OK"
        except (OSError, KeyError):
            return False
        if ok:
            telemetry.count("peer_snap.sends")
            telemetry.count("peer_snap.sent_bytes", len(blob))
        return ok

    def fetch(self, holder_rank, from_rank, step, timeout=5.0):
        """Pull ``from_rank``'s snapshot at ``step`` out of
        ``holder_rank``'s RAM; None when the holder doesn't have it.
        CRC-validated — raises CheckpointCorrupt on a torn transfer."""
        if holder_rank == self.rank:
            return self.own_at(step) if from_rank == self.rank else \
                self._local_fetch(from_rank, step)
        addr = self._addr_of(holder_rank)
        if not addr:
            return None
        frame = _PEER_MAGIC + struct.pack(
            _PEER_HDR, _PEER_GET, int(from_rank), int(step), 0, 0, 0)
        try:
            with socket.create_connection(
                    (addr["host"], addr["port"]), timeout=timeout) as c:
                c.sendall(frame)
                found, crc, nbytes = struct.unpack(
                    "<BIQ", _recv_exact(c, 13))
                if not found:
                    return None
                blob = _recv_exact(c, nbytes)
        except (OSError, KeyError):
            return None
        if zlib.crc32(blob) & 0xffffffff != crc:
            raise CheckpointCorrupt(
                f"peer snapshot rank {from_rank} step {step} from "
                f"holder {holder_rank}: checksum mismatch")
        telemetry.count("peer_snap.fetches")
        return _peer_unwrap(pickle.loads(blob))[0]

    def _local_fetch(self, from_rank, step):
        with self._lock:
            held = self._held.get(int(from_rank), {}).get(int(step))
        if held is None:
            return None
        return _peer_unwrap(pickle.loads(held[1]))[0]


def _apply_template(state, template, path="$"):
    """Walk state and template in lockstep: array leaves are validated
    against the template leaf (shape/dtype where it declares them) and
    ``jax.device_put`` onto its sharding.  Hard `MXNetError` on any
    structure/shape/dtype mismatch — an elastic restore that silently
    mis-assigns tensors is worse than one that refuses."""
    import numpy as np

    def walk(s, t, path):
        if t is None:
            return s
        if isinstance(s, dict):
            if not isinstance(t, dict):
                raise MXNetError(f"template mismatch at {path}: state "
                                 f"has dict, template {type(t).__name__}")
            if set(s) != set(t):
                missing = sorted(set(s) - set(t))
                extra = sorted(set(t) - set(s))
                raise MXNetError(
                    f"template mismatch at {path}: keys differ "
                    f"(missing from template: {missing}, "
                    f"extra in template: {extra})")
            return {k: walk(v, t[k], f"{path}.{k}") for k, v in s.items()}
        if isinstance(s, (list, tuple)):
            if not isinstance(t, (list, tuple)) or len(s) != len(t):
                raise MXNetError(
                    f"template mismatch at {path}: state has "
                    f"{type(s).__name__}[{len(s)}], template "
                    f"{type(t).__name__}"
                    f"[{len(t) if isinstance(t, (list, tuple)) else '?'}]")
            out = [walk(v, tv, f"{path}[{i}]")
                   for i, (v, tv) in enumerate(zip(s, t))]
            return out if isinstance(s, list) else tuple(out)
        if isinstance(s, np.ndarray):
            return _place_leaf(s, t, path)
        return s   # scalar: template position is ignored

    return walk(state, template, path)


def _place_leaf(arr, tmpl, path):
    import numpy as np

    tshape = getattr(tmpl, "shape", None)
    tdtype = getattr(tmpl, "dtype", None)
    if tshape is not None and tuple(tshape) != tuple(arr.shape):
        raise MXNetError(
            f"template mismatch at {path}: checkpoint leaf has shape "
            f"{tuple(arr.shape)}, template wants {tuple(tshape)}")
    if tdtype is not None and np.dtype(tdtype) != arr.dtype:
        raise MXNetError(
            f"template mismatch at {path}: checkpoint leaf has dtype "
            f"{arr.dtype}, template wants {np.dtype(tdtype)}")
    import jax
    from jax.sharding import Sharding

    target = tmpl
    if not isinstance(tmpl, Sharding):
        target = getattr(tmpl, "sharding", None)
        if target is None:
            raise MXNetError(
                f"template leaf at {path} is {type(tmpl).__name__}; "
                f"expected a jax Sharding, an array, or a "
                f"ShapeDtypeStruct carrying a sharding")
    return jax.device_put(arr, target)


def make_checkpointer(directory, max_to_keep=3, async_save=None,
                      backend=None, logger=None, **kwargs):
    """Pick a checkpoint engine (`MXTPU_CKPT_BACKEND` or ``backend=``):

    - ``"native"`` (default): :class:`AsyncCheckpointer` — async saves,
      two-phase multi-host commit, elastic restore, no extra deps.
    - ``"orbax"``: :class:`ShardedCheckpointer`; falls back to native
      (with a log line) when orbax is not installed.
    - ``"local"``: `resilience.LocalCheckpointer` (synchronous,
      single-host).
    """
    backend = (backend or os.environ.get("MXTPU_CKPT_BACKEND")
               or "native").lower()
    log = (logger.info if logger is not None
           else lambda m: sys.stderr.write(f"[checkpoint] {m}\n"))
    if backend == "orbax":
        try:
            import orbax.checkpoint     # noqa: F401

            log("checkpoint backend: orbax (ShardedCheckpointer)")
            return ShardedCheckpointer(
                directory, max_to_keep=max_to_keep,
                async_save=True if async_save is None else async_save)
        except ImportError:
            log("checkpoint backend: orbax requested but not installed; "
                "falling back to the native async engine")
            backend = "native"
    if backend == "local":
        from .resilience import LocalCheckpointer

        log("checkpoint backend: local (synchronous, single-host)")
        return LocalCheckpointer(directory, max_to_keep=max_to_keep)
    if backend != "native":
        raise MXNetError(f"make_checkpointer: unknown backend "
                         f"{backend!r} (native / orbax / local)")
    ck = AsyncCheckpointer(directory, max_to_keep=max_to_keep,
                           async_save=async_save, logger=logger,
                           **kwargs)
    log(f"checkpoint backend: native (async={ck.async_save}, "
        f"rank {ck.rank}/{ck.world_size})")
    return ck


def _gluon_walk_state(s, fn):
    if isinstance(s, (list, tuple)):
        out = [_gluon_walk_state(v, fn) for v in s]
        return out if isinstance(s, list) else tuple(out)
    return fn(s)


def _gluon_trainer_state(trainer):
    """`trainer_state` for the imperative gluon Trainer: parameters in
    trainer order, optimizer states keyed by trainer index, and the
    optimizer's update counters — everything `load_trainer_state` needs
    to resume the captured/eager step bitwise."""
    upd = trainer._updaters[0]
    o = trainer._optimizer
    idxs = sorted(upd.states)
    return snapshot_to_host({
        "params": [p.data() for p in trainer._params],
        "opt_state": [upd.states[i] for i in idxs],
        "opt_index": [int(i) for i in idxs],
        "num_update": int(o.num_update),
        "update_counts": {str(k): int(v)
                          for k, v in o._index_update_count.items()},
    })


def _gluon_trainer_template(trainer):
    """`trainer_state_template` for the gluon Trainer: the CURRENT
    parameter placements (`parallel.shard_model`'s NamedShardings)
    become the restore targets; weight-shaped optimizer state re-lays
    with its weight, everything else restores unplaced (host numpy,
    re-placed by `load_trainer_state`)."""
    from jax.sharding import NamedSharding

    upd = trainer._updaters[0]
    idxs = sorted(upd.states)

    def sh_of(p):
        s = getattr(p.data()._data, "sharding", None)
        return s if isinstance(s, NamedSharding) else None

    def state_tmpl(st, sh, wshape):
        def leaf(v):
            if hasattr(v, "__array__") and \
                    tuple(getattr(v, "shape", ())) == wshape:
                return sh
            return None
        return _gluon_walk_state(st, leaf)

    shs = [sh_of(p) for p in trainer._params]
    return {
        "params": shs,
        "opt_state": [state_tmpl(upd.states[i], shs[i],
                                 tuple(trainer._params[i].shape))
                      for i in idxs],
        "opt_index": None,
        "num_update": None,
        "update_counts": None,
    }


def _gluon_load_trainer_state(trainer, state):
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding

    from .ndarray import _from_jax

    upd = trainer._updaters[0]
    o = trainer._optimizer
    for p, v in zip(trainer._params, state["params"]):
        nd = p.data()
        sh = getattr(nd._data, "sharding", None)
        if isinstance(sh, NamedSharding):
            nd._set_data(jax.device_put(v, sh))
        else:
            nd._set_data(jnp.asarray(v))
    for i, st in zip(state["opt_index"], state["opt_state"]):
        p = trainer._params[i]
        sh = getattr(p.data()._data, "sharding", None)
        wshape = tuple(p.shape)

        def leaf(v, _sh=sh, _ws=wshape):
            if not hasattr(v, "__array__"):
                return v
            if isinstance(_sh, NamedSharding) and \
                    tuple(getattr(v, "shape", ())) == _ws:
                return _from_jax(jax.device_put(v, _sh))
            return _from_jax(jnp.asarray(v))

        upd.states[int(i)] = _gluon_walk_state(st, leaf)
        upd.states_synced[int(i)] = True
    o.num_update = int(state["num_update"])
    o._index_update_count = {int(k): int(v) for k, v
                             in state["update_counts"].items()}
    return trainer


def trainer_state(trainer):
    """Extract a trainer's full state as a SNAPSHOT pytree.

    Accepts a `parallel.ShardedTrainer` or an imperative
    `gluon.Trainer` (duck-typed on ``_param_vals``) — the captured-step
    path checkpoints through the same template machinery as the
    compiled one.

    Every leaf is a host copy (`snapshot_to_host`), never a live
    reference into the trainer: the trainer's buffers are donated to the
    next compiled step (which invalidates them) and its lists/dicts are
    mutated in place — an async save reading live references would
    serialize garbage.  Restoring this snapshot is bitwise-identical no
    matter how far the trainer trained on after the call.
    """
    if not hasattr(trainer, "_param_vals"):
        return _gluon_trainer_state(trainer)
    return snapshot_to_host({
        "params": list(trainer._param_vals),
        "opt_state": [list(s) for s in trainer._opt_state],
        "aux": dict(trainer._aux_vals),
        "num_update": trainer._num_update,
    })


def trainer_state_template(trainer):
    """The elastic-restore ``template`` matching `trainer_state`'s
    structure: array positions hold this trainer's `NamedSharding`s, so
    a checkpoint written under any world size/mesh re-lays onto THIS
    trainer's mesh (`AsyncCheckpointer.restore(step, template=...)`).
    Duck-typed like `trainer_state`."""
    from jax.sharding import NamedSharding, PartitionSpec

    if not hasattr(trainer, "_param_vals"):
        return _gluon_trainer_template(trainer)
    repl = NamedSharding(trainer.mesh, PartitionSpec())
    return {
        "params": list(trainer._param_shardings),
        "opt_state": [[sh for _ in states] for states, sh in
                      zip(trainer._opt_state, trainer._param_shardings)],
        "aux": {k: repl for k in trainer._aux_vals},
        "num_update": None,
    }


def load_trainer_state(trainer, state):
    """Load a restored pytree back into a trainer (duck-typed like
    `trainer_state`)."""
    import jax

    if not hasattr(trainer, "_param_vals"):
        return _gluon_load_trainer_state(trainer, state)
    trainer._param_vals = [
        jax.device_put(v, s) for v, s in
        zip(state["params"], trainer._param_shardings)]
    trainer._opt_state = [
        tuple(jax.device_put(x, sh) for x in st)
        for st, sh in zip(state["opt_state"], trainer._param_shardings)]
    from jax.sharding import NamedSharding, PartitionSpec

    repl = NamedSharding(trainer.mesh, PartitionSpec())
    trainer._aux_vals = {k: jax.device_put(v, repl)
                         for k, v in state["aux"].items()}
    trainer._num_update = int(state["num_update"])
    trainer.sync_params()
    return trainer


class PreemptionHandler:
    """Checkpoint on SIGTERM (TPU preemption notice).  Reference story is
    'restart from the last epoch checkpoint' (SURVEY §5.3); on TPU we get
    a grace window — snapshot mid-epoch state and exit cleanly.

    Usable as a context manager (``with PreemptionHandler(...):``), and
    chains to any previously-installed SIGTERM handler so stacking with
    an outer supervisor (e.g. a launcher's own grace logic) keeps both
    alive."""

    def __init__(self, checkpointer, get_state, get_step):
        self._ckpt = checkpointer
        self._get_state = get_state
        self._get_step = get_step
        self.preempted = threading.Event()
        self._restored = False
        self._prev = signal.signal(signal.SIGTERM, self._on_sigterm)

    def _on_sigterm(self, signum, frame):
        self.preempted.set()
        # chain: a previously-installed python handler still runs (the
        # reference bug was dropping it — an outer supervisor's grace
        # logic silently disabled)
        if callable(self._prev):
            self._prev(signum, frame)

    def maybe_checkpoint(self):
        """Call at step boundaries; saves + returns True when preempted.

        If the checkpointer already has an in-flight async save, the
        grace window is spent COMPLETING that commit rather than
        starting a new one — the pending snapshot is consistent and
        already half-written; racing a second save against the clock
        risks ending the grace period with neither committed.
        """
        if not self.preempted.is_set():
            return False
        in_flight = getattr(self._ckpt, "in_flight", None)
        if in_flight is not None and in_flight():
            self._ckpt.wait()
            return True
        self._ckpt.save(self._get_step(), self._get_state())
        self._ckpt.wait()
        return True

    def restore_handler(self):
        if self._restored:
            return
        # signal.signal rejects None (getsignal returns None for handlers
        # not installed from python) — fall back to the default action
        signal.signal(signal.SIGTERM,
                      self._prev if self._prev is not None
                      else signal.SIG_DFL)
        self._restored = True

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.restore_handler()
        return False
