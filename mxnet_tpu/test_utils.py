"""Testing utilities.

Reference parity: python/mxnet/test_utils.py — the testing backbone
(SURVEY.md §4): assert_almost_equal, check_numeric_gradient,
check_consistency, rand_ndarray, default_context, simple_forward.

The reference's CPU↔GPU consistency oracle maps to CPU-jax ↔ TPU here
(``check_consistency`` runs the same function on both backends when both
are visible).
"""

from __future__ import annotations

import os

import numpy as np

from .base import MXNetError
from .context import Context, cpu, current_context
from .ndarray.ndarray import NDArray, _from_jax


def default_context():
    """Env-switchable test context (reference: default_context +
    MXNET_TEST_DEFAULT_CONTEXT)."""
    name = os.environ.get("MXNET_TEST_DEFAULT_CONTEXT", "")
    if name:
        dev, _, idx = name.partition("(")
        idx = int(idx.rstrip(")")) if idx else 0
        return Context(dev.strip(), idx)
    return current_context()


def default_dtype():
    return np.float32


def _as_np(x):
    if isinstance(x, NDArray):
        return x.asnumpy()
    return np.asarray(x)


def same(a, b):
    return np.array_equal(_as_np(a), _as_np(b))


def almost_equal(a, b, rtol=None, atol=None, equal_nan=False):
    a, b = _as_np(a), _as_np(b)
    return np.allclose(a, b,
                       rtol=1e-5 if rtol is None else rtol,
                       atol=1e-20 if atol is None else atol,
                       equal_nan=equal_nan)


def assert_almost_equal(a, b, rtol=None, atol=None, names=("a", "b"),
                        equal_nan=False):
    """Dtype-aware tolerance comparison (reference:
    assert_almost_equal)."""
    a_np, b_np = _as_np(a), _as_np(b)
    if rtol is None or atol is None:
        dt = np.result_type(a_np.dtype, b_np.dtype)
        defaults = {np.dtype(np.float16): (1e-2, 1e-3),
                    np.dtype(np.float32): (1e-4, 1e-5),
                    np.dtype(np.float64): (1e-6, 1e-7)}
        d_rtol, d_atol = defaults.get(np.dtype(dt), (1e-4, 1e-5))
        rtol = rtol if rtol is not None else d_rtol
        atol = atol if atol is not None else d_atol
    np.testing.assert_allclose(a_np, b_np, rtol=rtol, atol=atol,
                               equal_nan=equal_nan,
                               err_msg=f"{names[0]} vs {names[1]}")


def rand_shape_nd(ndim, dim=10):
    return tuple(np.random.randint(1, dim + 1, size=ndim))


def rand_ndarray(shape, stype="default", density=None, dtype=np.float32,
                 scale=1.0):
    from . import ndarray as nd

    arr = nd.array(np.random.uniform(-scale, scale,
                                     shape).astype(dtype))
    return arr.tostype(stype) if stype != "default" else arr


def random_arrays(*shapes):
    arrays = [np.random.randn(*s).astype(np.float32) for s in shapes]
    if len(arrays) == 1:
        return arrays[0]
    return arrays


def list_gpus():
    """Reference: mx.test_utils.list_gpus — accelerator indices."""
    import jax

    try:
        return [d.id for d in jax.devices() if d.platform != "cpu"]
    except Exception:
        return []


def simple_forward(fn, *inputs, **kwargs):
    from . import ndarray as nd

    arrays = [nd.array(np.asarray(i)) if not isinstance(i, NDArray) else i
              for i in inputs]
    out = fn(*arrays, **kwargs)
    if isinstance(out, (list, tuple)):
        return [o.asnumpy() for o in out]
    return out.asnumpy()


def check_numeric_gradient(fn, inputs, eps=1e-4, rtol=1e-2, atol=1e-4,
                           argnums=None):
    """Finite-difference check of autograd gradients (reference:
    check_numeric_gradient — the op-level correctness oracle).

    fn: callable over NDArrays returning one NDArray (any shape; gradient
    of sum is checked).  inputs: list of numpy arrays.
    """
    from . import autograd
    from . import ndarray as nd

    inputs = [np.asarray(x, dtype=np.float64).astype(np.float32)
              for x in inputs]
    if argnums is None:
        argnums = range(len(inputs))

    arrs = [nd.array(x) for x in inputs]
    for a in arrs:
        a.attach_grad()
    with autograd.record():
        out = fn(*arrs)
        loss = out.sum() if hasattr(out, "sum") else sum(
            o.sum() for o in out)
    loss.backward()
    analytic = [a.grad.asnumpy() for a in arrs]

    for i in argnums:
        x = inputs[i]
        numeric = np.zeros_like(x)
        flat = x.reshape(-1)
        num_flat = numeric.reshape(-1)
        for j in range(flat.size):
            orig = flat[j]
            flat[j] = orig + eps
            plus = _loss_of(fn, inputs, nd)
            flat[j] = orig - eps
            minus = _loss_of(fn, inputs, nd)
            flat[j] = orig
            num_flat[j] = (plus - minus) / (2 * eps)
        np.testing.assert_allclose(
            analytic[i], numeric, rtol=rtol, atol=atol,
            err_msg=f"gradient mismatch for input {i}")


def _loss_of(fn, inputs, nd):
    out = fn(*[nd.array(x) for x in inputs])
    if isinstance(out, (list, tuple)):
        return float(sum(float(o.sum().asscalar()) for o in out))
    return float(out.sum().asscalar())


def check_symbolic_forward(fn, inputs, expected, rtol=1e-4, atol=1e-5):
    """Run fn on inputs, compare with expected numpy outputs."""
    from . import ndarray as nd

    out = fn(*[nd.array(np.asarray(x)) for x in inputs])
    outs = out if isinstance(out, (list, tuple)) else [out]
    expected = expected if isinstance(expected, (list, tuple)) \
        else [expected]
    for o, e in zip(outs, expected):
        assert_almost_equal(o, e, rtol=rtol, atol=atol)


def check_symbolic_backward(fn, inputs, out_grads, expected_grads,
                            rtol=1e-4, atol=1e-5):
    from . import autograd
    from . import ndarray as nd

    arrs = [nd.array(np.asarray(x)) for x in inputs]
    for a in arrs:
        a.attach_grad()
    with autograd.record():
        out = fn(*arrs)
    out.backward(nd.array(np.asarray(out_grads[0]))
                 if out_grads else None)
    for a, e in zip(arrs, expected_grads):
        if e is None:
            continue
        assert_almost_equal(a.grad, e, rtol=rtol, atol=atol)


def check_consistency(fn, inputs, backends=("cpu",), rtol=1e-4,
                      atol=1e-5):
    """Cross-backend consistency oracle (reference: the CPU↔GPU sweep in
    tests/python/gpu/test_operator_gpu.py; here CPU-jax ↔ TPU)."""
    import jax

    results = []
    for backend in backends:
        try:
            devs = jax.devices(backend)
        except RuntimeError:
            continue
        import jax.numpy as jnp

        args = [jax.device_put(jnp.asarray(np.asarray(x)), devs[0])
                for x in inputs]
        results.append((backend, np.asarray(fn(*args))))
    for (b1, r1), (b2, r2) in zip(results, results[1:]):
        np.testing.assert_allclose(
            r1, r2, rtol=rtol, atol=atol,
            err_msg=f"inconsistent between {b1} and {b2}")
    return results


def discover_type(dtype):
    return np.dtype(dtype)
