"""Automatic mixed precision.

Reference parity: python/mxnet/contrib/amp (≥1.6; flagged in SURVEY §2.3 as
likely absent in the fork — provided here regardless since bf16 is the
native MXU dtype).

TPU-first: the default policy is **bfloat16**, which needs NO loss scaling
(same exponent range as f32) — ``amp.init()`` just casts model compute to
bf16 and keeps normalization statistics + optimizer master state in f32
(multi_precision).  A float16 policy with ``DynamicLossScaler`` is provided
for parity with GPU-style AMP.
"""

from __future__ import annotations

import numpy as _np

from .base import MXNetError

_STATE = {"initialized": False, "dtype": "bfloat16"}


def init(target_dtype="bfloat16"):
    """Enable AMP defaults (reference: amp.init).  On TPU this just
    records the policy; casting happens per-model via init_block/convert.
    """
    assert target_dtype in ("bfloat16", "float16")
    _STATE["initialized"] = True
    _STATE["dtype"] = target_dtype


def init_trainer(trainer):
    """Switch a Trainer's optimizer to multi-precision master weights
    (reference: amp.init_trainer)."""
    trainer._optimizer.multi_precision = True
    return trainer


def convert_block(block, target_dtype=None):
    """Cast a gluon block's compute to the AMP dtype, keeping
    normalization layers in f32 (their cast() override already pins
    BatchNorm statistics to f32)."""
    target_dtype = target_dtype or _STATE["dtype"]
    block.cast(target_dtype)
    return block


init_block = convert_block


def convert_model(sym, arg_params, aux_params, target_dtype=None):
    """Symbol-path conversion (reference: amp.convert_model): cast params;
    the graph computes in the param dtype."""
    target_dtype = target_dtype or _STATE["dtype"]
    cast = {k: v.astype(target_dtype) for k, v in arg_params.items()}
    aux = {k: v.astype(target_dtype) for k, v in aux_params.items()}
    return sym, cast, aux


class DynamicLossScaler:
    """Loss scaling for float16 training (reference: the AMP loss scaler;
    unnecessary under bfloat16).

    ``tolerance`` is the fairseq-style overflow budget: on an overflow
    the scale halves only when the fraction of overflowed steps since
    the last rescale is at least ``tolerance``; the default 0.0 means
    every overflow halves (the classic behavior).  Growth is capped at
    ``max(init_scale, 2**16)`` — an unbounded doubling schedule would
    walk the scale to f32 infinity during a long clean stretch.
    """

    def __init__(self, init_scale=2.0 ** 16, scale_factor=2.0,
                 scale_window=2000, tolerance=0.0):
        self.loss_scale = init_scale
        self.scale_factor = scale_factor
        self.scale_window = scale_window
        self.tolerance = tolerance
        self._max_scale = max(init_scale, 2.0 ** 16)
        self._unskipped = 0
        self._iter = 0
        self._last_rescale_iter = 0
        self._overflows_since_rescale = 0

    def scale(self, loss):
        return loss * self.loss_scale

    def unscale(self, grads):
        """Return the gradients divided by the current scale.  JAX
        arrays are immutable, so this RETURNS new arrays — it cannot
        rewrite the inputs in place (the reference's ``g *= inv`` was a
        silent no-op here).  The Trainer path does not need this at all:
        it folds ``1/loss_scale`` into ``rescale_grad`` inside the fused
        step."""
        inv = 1.0 / self.loss_scale
        return [g * inv for g in grads]

    def has_overflow(self, grads):
        """One fused device reduction + ONE host readback over all
        gradients (the per-gradient ``asnumpy()`` loop this replaces
        forced a pipeline bubble per parameter)."""
        from . import numerics

        raws = []
        for g in grads:
            raw = getattr(g, "_data", None)
            raws.append(raw if raw is not None else _np.asarray(g))
        if not raws:
            return False
        guard = numerics.StepGuard(numerics.grad_health(raws))
        return not guard.healthy

    def update_scale(self, overflow):
        """Halve on overflow (subject to ``tolerance``); double after
        scale_window clean steps, capped at the growth ceiling."""
        self._iter += 1
        if overflow:
            self._overflows_since_rescale += 1
            pct = self._overflows_since_rescale / \
                max(1, self._iter - self._last_rescale_iter)
            if pct >= self.tolerance:
                self.loss_scale = max(
                    self.loss_scale / self.scale_factor, 1.0)
                self._last_rescale_iter = self._iter
                self._overflows_since_rescale = 0
            self._unskipped = 0
        else:
            self._unskipped += 1
            if self._unskipped >= self.scale_window:
                self.loss_scale = min(self.loss_scale * self.scale_factor,
                                      self._max_scale)
                self._last_rescale_iter = self._iter
                self._overflows_since_rescale = 0
                self._unskipped = 0
        return self.loss_scale


def scale_loss(loss, trainer):
    """Context-style helper (reference: with amp.scale_loss(...) as L)."""
    import contextlib

    @contextlib.contextmanager
    def ctx():
        if _STATE["dtype"] == "bfloat16":
            yield loss  # bf16 needs no scaling
        else:
            scaler = getattr(trainer, "_amp_loss_scaler", None)
            if scaler is None:
                scaler = DynamicLossScaler()
                trainer._amp_loss_scaler = scaler
            trainer._scale = 1.0 / scaler.loss_scale
            yield loss * scaler.loss_scale
    return ctx()
