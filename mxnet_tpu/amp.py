"""Automatic mixed precision.

Reference parity: python/mxnet/contrib/amp (≥1.6; flagged in SURVEY §2.3 as
likely absent in the fork — provided here regardless since bf16 is the
native MXU dtype).

TPU-first: the default policy is **bfloat16**, which needs NO loss scaling
(same exponent range as f32) — ``amp.init()`` just casts model compute to
bf16 and keeps normalization statistics + optimizer master state in f32
(multi_precision).  A float16 policy with ``DynamicLossScaler`` is provided
for parity with GPU-style AMP.
"""

from __future__ import annotations

import numpy as _np

from .base import MXNetError

_STATE = {"initialized": False, "dtype": "bfloat16"}


def init(target_dtype="bfloat16"):
    """Enable AMP defaults (reference: amp.init).  On TPU this just
    records the policy; casting happens per-model via init_block/convert.
    """
    assert target_dtype in ("bfloat16", "float16")
    _STATE["initialized"] = True
    _STATE["dtype"] = target_dtype


def init_trainer(trainer):
    """Switch a Trainer's optimizer to multi-precision master weights
    (reference: amp.init_trainer)."""
    trainer._optimizer.multi_precision = True
    return trainer


def convert_block(block, target_dtype=None):
    """Cast a gluon block's compute to the AMP dtype, keeping
    normalization layers in f32 (their cast() override already pins
    BatchNorm statistics to f32)."""
    target_dtype = target_dtype or _STATE["dtype"]
    block.cast(target_dtype)
    return block


init_block = convert_block


def convert_model(sym, arg_params, aux_params, target_dtype=None):
    """Symbol-path conversion (reference: amp.convert_model): cast params;
    the graph computes in the param dtype."""
    target_dtype = target_dtype or _STATE["dtype"]
    cast = {k: v.astype(target_dtype) for k, v in arg_params.items()}
    aux = {k: v.astype(target_dtype) for k, v in aux_params.items()}
    return sym, cast, aux


class DynamicLossScaler:
    """Loss scaling for float16 training (reference: the AMP loss scaler;
    unnecessary under bfloat16)."""

    def __init__(self, init_scale=2.0 ** 16, scale_factor=2.0,
                 scale_window=2000, tolerance=0.0):
        self.loss_scale = init_scale
        self.scale_factor = scale_factor
        self.scale_window = scale_window
        self._unskipped = 0

    def scale(self, loss):
        return loss * self.loss_scale

    def unscale(self, grads):
        inv = 1.0 / self.loss_scale
        for g in grads:
            g *= inv
        return grads

    def has_overflow(self, grads):
        for g in grads:
            a = g.asnumpy() if hasattr(g, "asnumpy") else _np.asarray(g)
            if not _np.all(_np.isfinite(a)):
                return True
        return False

    def update_scale(self, overflow):
        """Halve on overflow; double after scale_window clean steps."""
        if overflow:
            self.loss_scale = max(self.loss_scale / self.scale_factor, 1.0)
            self._unskipped = 0
        else:
            self._unskipped += 1
            if self._unskipped >= self.scale_window:
                self.loss_scale *= self.scale_factor
                self._unskipped = 0
        return self.loss_scale


def scale_loss(loss, trainer):
    """Context-style helper (reference: with amp.scale_loss(...) as L)."""
    import contextlib

    @contextlib.contextmanager
    def ctx():
        if _STATE["dtype"] == "bfloat16":
            yield loss  # bf16 needs no scaling
        else:
            scaler = getattr(trainer, "_amp_loss_scaler", None)
            if scaler is None:
                scaler = DynamicLossScaler()
                trainer._amp_loss_scaler = scaler
            trainer._scale = 1.0 / scaler.loss_scale
            yield loss * scaler.loss_scale
    return ctx()
