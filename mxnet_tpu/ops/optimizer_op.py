"""Fused optimizer update ops.

Reference parity: src/operator/optimizer_op.cc / optimizer_op-inl.h —
`sgd_update`, `sgd_mom_update`, `adam_update`, `nag_mom_update`,
`rmsprop_update`, `rmspropalex_update`, `ftrl_update`, `signsgd_update`,
`signum_update`, `lamb_update_phase1/2`, and the multi-precision (`mp_*`)
variants that keep an fp32 master weight next to fp16 model weights.

TPU-first design: each update is one pure JAX function returning
``(new_weight, *new_states)``; XLA fuses the whole update into a single
elementwise kernel (the reason the reference hand-fused these in CUDA).
The registered NDArray wrappers are *opaque*: they apply the reference's
in-place mutation contract (states mutate silently, ``out=`` receives the
weight) by handle-swapping.  Inside jit/hybridize traces call the pure
functions directly (``mxnet_tpu.ops.optimizer_op.sgd_update_pure`` etc.) —
this is what ``gluon.Trainer``'s fused step uses.
"""

from __future__ import annotations

import jax.numpy as jnp

from .registry import register


def _rescale(grad, rescale_grad, clip_gradient):
    grad = grad * rescale_grad
    if clip_gradient is not None and clip_gradient >= 0:
        grad = jnp.clip(grad, -clip_gradient, clip_gradient)
    return grad


# -- pure updates (returning (weight, *states)) --------------------------------

def sgd_update_pure(weight, grad, lr, wd=0.0, rescale_grad=1.0,
                    clip_gradient=-1.0, lazy_update=True):
    grad = _rescale(grad, rescale_grad, clip_gradient)
    return (weight - lr * (grad + wd * weight),)


def sgd_mom_update_pure(weight, grad, mom, lr, momentum=0.0, wd=0.0,
                        rescale_grad=1.0, clip_gradient=-1.0,
                        lazy_update=True):
    grad = _rescale(grad, rescale_grad, clip_gradient)
    mom = momentum * mom - lr * (grad + wd * weight)
    return weight + mom, mom


def nag_mom_update_pure(weight, grad, mom, lr, momentum=0.0, wd=0.0,
                        rescale_grad=1.0, clip_gradient=-1.0):
    # reference python fallback (python/mxnet/optimizer/optimizer.py NAG):
    #   mom = momentum*mom + grad + wd*w;  w -= lr*(grad + wd*w + momentum*mom)
    grad = _rescale(grad, rescale_grad, clip_gradient) + wd * weight
    mom = momentum * mom + grad
    return weight - lr * (grad + momentum * mom), mom


def adam_update_pure(weight, grad, mean, var, lr, beta1=0.9, beta2=0.999,
                     epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                     clip_gradient=-1.0, lazy_update=True):
    # bias correction is folded into `lr` by the Optimizer (reference
    # behavior: python/mxnet/optimizer/optimizer.py Adam computes lr_t).
    # reference AdamUpdate clips AFTER adding weight decay:
    # grad = clip(rescale*grad + wd*weight)
    grad = grad * rescale_grad + wd * weight
    if clip_gradient is not None and clip_gradient >= 0:
        grad = jnp.clip(grad, -clip_gradient, clip_gradient)
    mean = beta1 * mean + (1.0 - beta1) * grad
    var = beta2 * var + (1.0 - beta2) * jnp.square(grad)
    return weight - lr * mean / (jnp.sqrt(var) + epsilon), mean, var


def adamw_update_pure(weight, grad, mean, var, lr, beta1=0.9, beta2=0.999,
                      epsilon=1e-8, wd=0.0, eta=1.0, rescale_grad=1.0,
                      clip_gradient=-1.0):
    """Decoupled weight decay (reference: contrib adamw_update)."""
    grad = _rescale(grad, rescale_grad, clip_gradient)
    mean = beta1 * mean + (1.0 - beta1) * grad
    var = beta2 * var + (1.0 - beta2) * jnp.square(grad)
    return (weight - eta * (lr * mean / (jnp.sqrt(var) + epsilon)
                            + wd * weight), mean, var)


def rmsprop_update_pure(weight, grad, n, lr, gamma1=0.95, epsilon=1e-8,
                        wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                        clip_weights=-1.0):
    grad = _rescale(grad, rescale_grad, clip_gradient) + wd * weight
    n = (1.0 - gamma1) * jnp.square(grad) + gamma1 * n
    weight = weight - lr * grad / jnp.sqrt(n + epsilon)
    if clip_weights is not None and clip_weights > 0:
        weight = jnp.clip(weight, -clip_weights, clip_weights)
    return weight, n


def rmspropalex_update_pure(weight, grad, n, g, delta, lr, gamma1=0.95,
                            gamma2=0.9, epsilon=1e-8, wd=0.0,
                            rescale_grad=1.0, clip_gradient=-1.0,
                            clip_weights=-1.0):
    """Centered RMSProp (Graves 2013), reference rmspropalex_update."""
    grad = _rescale(grad, rescale_grad, clip_gradient) + wd * weight
    n = (1.0 - gamma1) * jnp.square(grad) + gamma1 * n
    g = (1.0 - gamma1) * grad + gamma1 * g
    delta = gamma2 * delta - lr * grad / jnp.sqrt(n - jnp.square(g) + epsilon)
    weight = weight + delta
    if clip_weights is not None and clip_weights > 0:
        weight = jnp.clip(weight, -clip_weights, clip_weights)
    return weight, n, g, delta


def ftrl_update_pure(weight, grad, z, n, lr, lamda1=0.01, beta=1.0, wd=0.0,
                     rescale_grad=1.0, clip_gradient=-1.0):
    grad = _rescale(grad, rescale_grad, clip_gradient)
    new_n = n + jnp.square(grad)
    z = z + grad - (jnp.sqrt(new_n) - jnp.sqrt(n)) / lr * weight
    weight = (-jnp.sign(z) * jnp.maximum(jnp.abs(z) - lamda1, 0.0)
              / ((beta + jnp.sqrt(new_n)) / lr + wd))
    return weight, z, new_n


def signsgd_update_pure(weight, grad, lr, wd=0.0, rescale_grad=1.0,
                        clip_gradient=-1.0):
    grad = _rescale(grad, rescale_grad, clip_gradient)
    return (weight - lr * (jnp.sign(grad) + wd * weight),)


def signum_update_pure(weight, grad, mom, lr, momentum=0.0, wd=0.0,
                       rescale_grad=1.0, clip_gradient=-1.0, wd_lh=0.0):
    grad = _rescale(grad, rescale_grad, clip_gradient)
    mom = momentum * mom - (1.0 - momentum) * (grad + wd * weight)
    weight = (1.0 - lr * wd_lh) * weight + lr * jnp.sign(mom)
    return weight, mom


def adagrad_update_pure(weight, grad, history, lr, epsilon=1e-7, wd=0.0,
                        rescale_grad=1.0, clip_gradient=-1.0):
    grad = _rescale(grad, rescale_grad, clip_gradient)
    history = history + jnp.square(grad)
    return (weight - lr * (grad / jnp.sqrt(history + epsilon) + wd * weight),
            history)


def adadelta_update_pure(weight, grad, acc_g, acc_delta, rho=0.9,
                         epsilon=1e-5, wd=0.0, rescale_grad=1.0,
                         clip_gradient=-1.0):
    grad = _rescale(grad, rescale_grad, clip_gradient) + wd * weight
    acc_g = rho * acc_g + (1.0 - rho) * jnp.square(grad)
    delta = (jnp.sqrt(acc_delta + epsilon) / jnp.sqrt(acc_g + epsilon)) * grad
    acc_delta = rho * acc_delta + (1.0 - rho) * jnp.square(delta)
    return weight - delta, acc_g, acc_delta


def lars_update_pure(weight, grad, mom, lr, eta=0.001, momentum=0.9,
                     wd=0.0, epsilon=1e-9, rescale_grad=1.0,
                     clip_gradient=-1.0):
    """LARS layer-wise adaptive SGD (reference: lars_update /
    preloaded_multi_sgd kernels ≥1.6 and the LBSGD python optimizer):
    the layer's lr is scaled by eta·||w|| / (||g|| + wd·||w|| + eps),
    then an SGD-momentum step runs with it."""
    grad = _rescale(grad, rescale_grad, clip_gradient)
    w_norm = jnp.linalg.norm(weight)
    g_norm = jnp.linalg.norm(grad)
    ratio = jnp.where((w_norm > 0.0) & (g_norm > 0.0),
                      eta * w_norm / (g_norm + wd * w_norm + epsilon),
                      1.0)
    lr = lr * ratio
    mom = momentum * mom - lr * (grad + wd * weight)
    return weight + mom, mom


def ftml_update_pure(weight, grad, d, v, z, lr, t=1, beta1=0.6,
                     beta2=0.999, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                     clip_grad=-1.0):
    """FTML — Follow The Moving Leader (reference: ftml_update kernel in
    optimizer_op.cc ≥1.2; Zheng & Kwok 2017).  States: d (denominator),
    v (second moment), z (leader accumulator); the reference folds wd
    into the gradient BEFORE clipping (unlike sgd/adam where clip comes
    first — same family of per-op quirks as adam_update's).  NOTE the
    reference names its clip knob ``clip_grad`` on this one op (not
    ``clip_gradient``)."""
    grad = grad * rescale_grad + wd * weight
    if clip_grad is not None and clip_grad >= 0:
        grad = jnp.clip(grad, -clip_grad, clip_grad)
    v = beta2 * v + (1.0 - beta2) * jnp.square(grad)
    d_t = (1.0 - beta1 ** t) / lr * (
        jnp.sqrt(v / (1.0 - beta2 ** t)) + epsilon)
    sigma = d_t - beta1 * d
    z = beta1 * z + (1.0 - beta1) * grad - sigma * weight
    return -z / d_t, d_t, v, z


def lamb_fused_update_pure(weight, grad, mean, var, lr, wd, denom1, denom2,
                           beta1=0.9, beta2=0.999, epsilon=1e-6,
                           rescale_grad=1.0, clip_gradient=-1.0,
                           lower_bound=-1.0, upper_bound=-1.0):
    """Single-dispatch LAMB step for the grouped Trainer path: phase1 +
    trust-ratio norms + phase2 in one program.  ``denom1``/``denom2``
    are the HOST-precomputed bias-correction denominators
    ``1 - beta**t`` so the step count is a traced scalar and never
    retraces; with ``bias_correction=False`` pass 1.0 — ``x / 1.0`` is
    an IEEE identity, keeping bitwise parity with phase1's uncorrected
    branch."""
    grad = _rescale(grad, rescale_grad, clip_gradient)
    mean = beta1 * mean + (1.0 - beta1) * grad
    var = beta2 * var + (1.0 - beta2) * jnp.square(grad)
    mhat = mean / denom1
    vhat = var / denom2
    g_new = mhat / (jnp.sqrt(vhat) + epsilon) + wd * weight
    r1 = jnp.linalg.norm(weight)
    r2 = jnp.linalg.norm(g_new)
    if lower_bound is not None and lower_bound > 0:
        r1 = jnp.maximum(r1, lower_bound)
    if upper_bound is not None and upper_bound > 0:
        r1 = jnp.minimum(r1, upper_bound)
    ratio = jnp.where((r1 > 0) & (r2 > 0), r1 / r2, 1.0)
    return weight - lr * ratio * g_new, mean, var


def ftml_fused_update_pure(weight, grad, d, v, z, c_over_lr, coef2, wd,
                           beta1=0.6, beta2=0.999, epsilon=1e-8,
                           rescale_grad=1.0, clip_grad=-1.0):
    """FTML step for the grouped Trainer path.  The step-count terms are
    host-precomputed exactly as ``ftml_update_pure`` applies them —
    ``c_over_lr = (1 - beta1**t) / lr`` and ``coef2 = 1 - beta2**t`` —
    so ``t`` never appears as a trace-shaping value."""
    grad = grad * rescale_grad + wd * weight
    if clip_grad is not None and clip_grad >= 0:
        grad = jnp.clip(grad, -clip_grad, clip_grad)
    v = beta2 * v + (1.0 - beta2) * jnp.square(grad)
    d_t = c_over_lr * (jnp.sqrt(v / coef2) + epsilon)
    sigma = d_t - beta1 * d
    z = beta1 * z + (1.0 - beta1) * grad - sigma * weight
    return -z / d_t, d_t, v, z


def lamb_update_phase1_pure(weight, grad, mean, var, t=1, beta1=0.9,
                            beta2=0.999, epsilon=1e-6, wd=0.0,
                            bias_correction=True, rescale_grad=1.0,
                            clip_gradient=-1.0):
    grad = _rescale(grad, rescale_grad, clip_gradient)
    mean = beta1 * mean + (1.0 - beta1) * grad
    var = beta2 * var + (1.0 - beta2) * jnp.square(grad)
    if bias_correction:
        mhat = mean / (1.0 - beta1 ** t)
        vhat = var / (1.0 - beta2 ** t)
    else:
        mhat, vhat = mean, var
    g_new = mhat / (jnp.sqrt(vhat) + epsilon) + wd * weight
    return g_new, mean, var


def lamb_update_phase2_pure(weight, g, r1, r2, lr, lower_bound=-1.0,
                            upper_bound=-1.0):
    if lower_bound is not None and lower_bound > 0:
        r1 = jnp.maximum(r1, lower_bound)
    if upper_bound is not None and upper_bound > 0:
        r1 = jnp.minimum(r1, upper_bound)
    ratio = jnp.where((r1 > 0) & (r2 > 0), r1 / r2, 1.0)
    return (weight - lr * ratio * g,)


# -- multi-precision variants (fp32 master weight, last positional state) ------

def _mp(pure_fn):
    def mp_fn(weight, grad, *states_and_w32, **kwargs):
        *states, weight32 = states_and_w32
        g32 = grad.astype(jnp.float32)
        out = pure_fn(weight32, g32, *states, **kwargs)
        new_w32, new_states = out[0], out[1:]
        return (new_w32.astype(weight.dtype),) + tuple(new_states) + \
            (new_w32,)
    return mp_fn


mp_sgd_update_pure = _mp(sgd_update_pure)
mp_sgd_mom_update_pure = _mp(sgd_mom_update_pure)
mp_nag_mom_update_pure = _mp(nag_mom_update_pure)
mp_adam_update_pure = _mp(adam_update_pure)
mp_lamb_update_phase1_pure = _mp(lamb_update_phase1_pure)
mp_lars_update_pure = _mp(lars_update_pure)


# -- NDArray wrappers (reference in-place mutation contract) -------------------

def _register_update(name, pure_fn):
    @register(name, opaque=True)
    def wrapper(*args, **kwargs):
        from ..ndarray.ndarray import NDArray, _from_jax

        out = kwargs.pop("out", None)
        kwargs.pop("name", None)
        if not any(isinstance(a, NDArray) for a in args):
            return pure_fn(*args, **kwargs)  # traced / pure path
        nd_states = [a for a in args[2:] if isinstance(a, NDArray)]
        raws = [a._data if isinstance(a, NDArray) else a for a in args]
        res = pure_fn(*raws, **kwargs)
        first, new_states = res[0], res[1:]
        for arr, new in zip(nd_states, new_states):
            arr._set_data(new)
        if out is not None:
            out._set_data(first)
            return out
        return _from_jax(first)

    wrapper.__name__ = name
    return wrapper


for _name, _fn in [
    ("sgd_update", sgd_update_pure),
    ("sgd_mom_update", sgd_mom_update_pure),
    ("nag_mom_update", nag_mom_update_pure),
    ("adam_update", adam_update_pure),
    ("adamw_update", adamw_update_pure),
    ("rmsprop_update", rmsprop_update_pure),
    ("rmspropalex_update", rmspropalex_update_pure),
    ("ftrl_update", ftrl_update_pure),
    ("signsgd_update", signsgd_update_pure),
    ("signum_update", signum_update_pure),
    ("adagrad_update", adagrad_update_pure),
    ("adadelta_update", adadelta_update_pure),
    ("lars_update", lars_update_pure),
    ("mp_lars_update", mp_lars_update_pure),
    ("ftml_update", ftml_update_pure),
    ("lamb_update_phase1", lamb_update_phase1_pure),
    ("lamb_update_phase2", lamb_update_phase2_pure),
    ("mp_sgd_update", mp_sgd_update_pure),
    ("mp_sgd_mom_update", mp_sgd_mom_update_pure),
    ("mp_nag_mom_update", mp_nag_mom_update_pure),
    ("mp_adam_update", mp_adam_update_pure),
    ("mp_lamb_update_phase1", mp_lamb_update_phase1_pure),
]:
    _register_update(_name, _fn)


# -- single-parameter jitted dispatch ------------------------------------------
#
# The per-parameter Updater path compiles each update into ONE cached XLA
# program instead of dispatching op-by-op.  Per-step host scalars (lr/wd/
# rescale_grad) enter as traced arguments cast to the weight dtype, so LR
# schedules never retrace; every other kwarg is a Python constant baked
# into the trace.  Keeping the same trace structure as the grouped
# multi-tensor path (optimizer/grouped.py) makes the two bitwise-equal:
# XLA's FMA contraction applies identically to both programs, where the
# old op-by-op eager sequence rounded every intermediate.

_DYN_ARGS = {
    "adadelta_update_pure": ("wd", "rescale_grad"),
    # t/lr fold into trace-time f64 constants exactly as the eager host
    # code computed them (retraces per step — fallback path only)
    "ftml_update_pure": ("wd", "rescale_grad"),
    "lamb_update_phase1_pure": ("wd", "rescale_grad"),
    "lamb_update_phase2_pure": ("lr",),
    "lamb_fused_update_pure": ("lr", "wd", "rescale_grad", "denom1",
                               "denom2"),
    "ftml_fused_update_pure": ("c_over_lr", "coef2", "wd", "rescale_grad"),
}
_DEFAULT_DYN = ("lr", "wd", "rescale_grad")

_SINGLE_CACHE = {}


def fused_dispatch(pure_fn, weight, grad, states, kwargs):
    """Run ``pure_fn(weight, grad, *states, **kwargs)`` as one cached
    jitted program (weight and states donated).  Raw jax arrays in, raw
    results out."""
    import numpy as _np

    import jax

    dyn_names = tuple(
        n for n in _DYN_ARGS.get(pure_fn.__name__, _DEFAULT_DYN)
        if n in kwargs)
    static_items = tuple(sorted(
        (k, v) for k, v in kwargs.items() if k not in dyn_names))
    key = (pure_fn, dyn_names, static_items)
    fn = _SINGLE_CACHE.get(key)
    if fn is None:
        static = dict(static_items)

        def one(w, g, ss, dyn):
            kw = dict(static)
            kw.update(dyn)
            return pure_fn(w, g, *ss, **kw)

        fn = jax.jit(one, donate_argnums=(0, 2))
        _SINGLE_CACHE[key] = fn
    dyn = {n: _np.asarray(kwargs[n], weight.dtype) for n in dyn_names}
    return fn(weight, grad, list(states), dyn)


PURE_UPDATES = {
    "sgd_update": sgd_update_pure,
    "sgd_mom_update": sgd_mom_update_pure,
    "nag_mom_update": nag_mom_update_pure,
    "adam_update": adam_update_pure,
    "adamw_update": adamw_update_pure,
    "rmsprop_update": rmsprop_update_pure,
    "rmspropalex_update": rmspropalex_update_pure,
    "ftrl_update": ftrl_update_pure,
    "signsgd_update": signsgd_update_pure,
    "signum_update": signum_update_pure,
    "adagrad_update": adagrad_update_pure,
    "adadelta_update": adadelta_update_pure,
    "lars_update": lars_update_pure,
    "ftml_update": ftml_update_pure,
}
