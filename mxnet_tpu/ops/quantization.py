"""Quantization ops (int8 inference).

Reference parity: src/operator/quantization/ (≥1.2) — quantize/quantize_v2,
dequantize, requantize, quantized_fully_connected, quantized_conv, and the
calibration helpers behind contrib.quantization.quantize_model.

TPU-first: int8 matmuls run on the MXU via lax.dot_general with int32
accumulation (the TPU analog of the reference's cuDNN/MKLDNN int8 paths);
scales ride alongside as min/max pairs exactly like the reference's
(data, min, max) triples.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register


@register("_contrib_quantize", aliases=("quantize",))
def quantize(data, min_range, max_range, out_type="int8"):
    """Affine-quantize to int8 using given range (reference:
    quantize.cc).  Returns (q, min, max)."""
    if out_type != "int8":
        raise NotImplementedError("only int8 quantization on TPU")
    scale = 127.0 / jnp.maximum(jnp.maximum(jnp.abs(min_range),
                                            jnp.abs(max_range)), 1e-8)
    q = jnp.clip(jnp.round(data * scale), -127, 127).astype(jnp.int8)
    r = 127.0 / scale
    return q, -r, r


@register("_contrib_quantize_v2", aliases=("quantize_v2",))
def quantize_v2(data, out_type="int8", min_calib_range=None,
                max_calib_range=None):
    """Quantize with self-computed or calibrated range (reference:
    quantize_v2.cc)."""
    if min_calib_range is None or max_calib_range is None:
        mn = jnp.min(data)
        mx = jnp.max(data)
    else:
        mn = jnp.asarray(min_calib_range)
        mx = jnp.asarray(max_calib_range)
    return quantize(data, mn, mx, out_type)


def _quant_levels(dtype):
    """int8 → 127, int32 → 2^31-1 (reference range convention: the
    min/max pair spans the full quantized dtype range)."""
    if jnp.dtype(dtype) == jnp.int32:
        return 2147483647.0
    return 127.0


@register("_contrib_dequantize", aliases=("dequantize",))
def dequantize(data, min_range, max_range, out_type="float32"):
    levels = _quant_levels(data.dtype)
    scale = jnp.maximum(jnp.abs(min_range), jnp.abs(max_range)) / levels
    return data.astype(jnp.float32) * scale


@register("_contrib_requantize", aliases=("requantize",))
def requantize(data, min_range, max_range, out_type="int8",
               min_calib_range=None, max_calib_range=None):
    """int32 accumulator → int8 with a new range (reference:
    requantize.cc)."""
    f = data.astype(jnp.float32) * (
        jnp.maximum(jnp.abs(min_range), jnp.abs(max_range))
        / _quant_levels(data.dtype))
    if min_calib_range is None:
        mn, mx = jnp.min(f), jnp.max(f)
    else:
        mn, mx = jnp.asarray(min_calib_range), \
            jnp.asarray(max_calib_range)
    return quantize(f, mn, mx, out_type)


@register("_contrib_quantized_fully_connected",
          aliases=("quantized_fully_connected",))
def quantized_fully_connected(data, weight, bias, min_data, max_data,
                              min_weight, max_weight, min_bias=None,
                              max_bias=None, num_hidden=None,
                              no_bias=False, flatten=True):
    """int8 × int8 → int32 FC on the MXU (reference:
    quantized_fully_connected.cc).  Returns (out_i32, min_out, max_out)."""
    if flatten and data.ndim > 2:
        data = data.reshape(data.shape[0], -1)
    out = lax.dot_general(
        data.astype(jnp.int8), weight.astype(jnp.int8),
        (((data.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32)
    s_data = jnp.maximum(jnp.abs(min_data), jnp.abs(max_data)) / 127.0
    s_w = jnp.maximum(jnp.abs(min_weight), jnp.abs(max_weight)) / 127.0
    out_scale = s_data * s_w
    if bias is not None and not no_bias:
        s_b = jnp.maximum(jnp.abs(min_bias), jnp.abs(max_bias)) / 127.0
        b_i32 = jnp.round(bias.astype(jnp.float32) * s_b
                          / out_scale).astype(jnp.int32)
        out = out + b_i32
    r = 2147483647.0 * out_scale
    return out, -r, r


@register("_contrib_quantized_conv", aliases=("quantized_conv",))
def quantized_conv(data, weight, bias, min_data, max_data, min_weight,
                   max_weight, min_bias=None, max_bias=None, kernel=None,
                   stride=None, pad=None, num_filter=None, num_group=1,
                   no_bias=False, layout=None):
    """int8 convolution with int32 accumulation (reference:
    quantized_conv.cc)."""
    from .nn import _pair, _conv_dn

    nd = data.ndim
    spatial = nd - 2
    stride = _pair(stride or 1, spatial)
    pad_t = _pair(pad or 0, spatial)
    dn = lax.conv_dimension_numbers(data.shape, weight.shape,
                                    _conv_dn(nd))
    out = lax.conv_general_dilated(
        data.astype(jnp.int8), weight.astype(jnp.int8),
        window_strides=stride, padding=[(p, p) for p in pad_t],
        dimension_numbers=dn, feature_group_count=num_group,
        preferred_element_type=jnp.int32)
    s_data = jnp.maximum(jnp.abs(min_data), jnp.abs(max_data)) / 127.0
    s_w = jnp.maximum(jnp.abs(min_weight), jnp.abs(max_weight)) / 127.0
    out_scale = s_data * s_w
    if bias is not None and not no_bias:
        s_b = jnp.maximum(jnp.abs(min_bias), jnp.abs(max_bias)) / 127.0
        b_i32 = jnp.round(bias.astype(jnp.float32) * s_b
                          / out_scale).astype(jnp.int32)
        out = out + b_i32.reshape((1, -1) + (1,) * spatial)
    r = 2147483647.0 * out_scale
    return out, -r, r
