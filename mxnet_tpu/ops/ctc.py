"""CTC loss.

Reference parity: src/operator/nn/ctc_loss.cc (warp-ctc / cuDNN CTC).

TPU-first: the log-alpha forward recursion is one ``lax.scan`` over time —
static shapes, fully batched, differentiable by JAX through the scan
(replacing the reference's hand-written backward).  Blank label index is 0
(the reference's default ``blank_label='first'``); real labels are ≥ 1;
``label`` entries < 1 beyond ``label_lengths`` are padding.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

_NEG_INF = -1e30


def _ctc_alpha(logp, ext, ext_valid, pred_lengths):
    """logp: (N,T,C) log-probs; ext: (N,S) extended labels (blank-interleaved,
    S=2L+1); ext_valid: (N,) valid extended length; pred_lengths: (N,)."""
    N, T, C = logp.shape
    S = ext.shape[1]
    # transition mask: can we skip from s-2 to s? (ext[s]!=blank and
    # ext[s]!=ext[s-2])
    ext_m2 = jnp.pad(ext, ((0, 0), (2, 0)), constant_values=-1)[:, :S]
    can_skip = (ext != 0) & (ext != ext_m2)

    emit0 = jnp.take_along_axis(logp[:, 0], ext, axis=1)  # (N,S)
    alpha0 = jnp.full((N, S), _NEG_INF)
    alpha0 = alpha0.at[:, 0].set(emit0[:, 0])
    alpha0 = alpha0.at[:, 1].set(jnp.where(ext_valid > 1, emit0[:, 1],
                                           _NEG_INF))

    def step(alpha, inputs):
        logp_t, t = inputs
        emit = jnp.take_along_axis(logp_t, ext, axis=1)  # (N,S)
        a_prev = alpha
        a_m1 = jnp.pad(alpha, ((0, 0), (1, 0)),
                       constant_values=_NEG_INF)[:, :S]
        a_m2 = jnp.pad(alpha, ((0, 0), (2, 0)),
                       constant_values=_NEG_INF)[:, :S]
        a_m2 = jnp.where(can_skip, a_m2, _NEG_INF)
        stacked = jnp.stack([a_prev, a_m1, a_m2], axis=0)
        new_alpha = jax.scipy.special.logsumexp(stacked, axis=0) + emit
        # freeze past the sequence end (reference: per-sample T_n)
        active = (t < pred_lengths)[:, None]
        return jnp.where(active, new_alpha, alpha), None

    ts = jnp.arange(1, T)
    alpha_T, _ = lax.scan(step, alpha0, (jnp.swapaxes(logp, 0, 1)[1:], ts))
    return alpha_T


@register("ctc_loss", aliases=("CTCLoss", "contrib_ctc_loss"))
def ctc_loss(pred, label, pred_lengths=None, label_lengths=None):
    """Negative log-likelihood per sequence.  pred: (N, T, C) unnormalized
    activations; label: (N, L) with classes in [1, C-1], padded with values
    < 1."""
    if hasattr(pred, "_data"):
        pred = pred._data
    if hasattr(label, "_data"):
        label = label._data
    label = label.astype(jnp.int32)
    N, T, C = pred.shape
    L = label.shape[1]
    if pred_lengths is None:
        pred_lengths = jnp.full((N,), T, dtype=jnp.int32)
    else:
        if hasattr(pred_lengths, "_data"):
            pred_lengths = pred_lengths._data
        pred_lengths = pred_lengths.astype(jnp.int32)
    if label_lengths is None:
        label_lengths = jnp.sum((label >= 1).astype(jnp.int32), axis=1)
    else:
        if hasattr(label_lengths, "_data"):
            label_lengths = label_lengths._data
        label_lengths = label_lengths.astype(jnp.int32)

    logp = jax.nn.log_softmax(pred, axis=-1)
    S = 2 * L + 1
    ext = jnp.zeros((N, S), dtype=jnp.int32)
    ext = ext.at[:, 1::2].set(jnp.maximum(label, 0))
    ext_valid = 2 * label_lengths + 1

    alpha_T = _ctc_alpha(logp, ext, ext_valid, pred_lengths)
    end = 2 * label_lengths  # blank after last label
    a_end = jnp.take_along_axis(alpha_T, end[:, None], axis=1)[:, 0]
    a_last = jnp.take_along_axis(alpha_T,
                                 jnp.maximum(end - 1, 0)[:, None],
                                 axis=1)[:, 0]
    a_last = jnp.where(label_lengths > 0, a_last, _NEG_INF)
    ll = jnp.logaddexp(a_end, a_last)
    return -ll
