"""Indexing / gather / scatter ops.

Reference parity: src/operator/tensor/indexing_op.cc (take, Embedding,
one_hot, gather_nd, scatter_nd, pick, batch_take).
"""

from __future__ import annotations

import jax.numpy as jnp

from .registry import register
from ..base import np_dtype


@register("take")
def take(a, indices, axis=0, mode="clip"):
    m = {"clip": "clip", "wrap": "wrap", "raise": "clip"}[mode]
    if a.shape[axis] > 2 ** 31 - 1:
        # large-tensor gather (INT64_TENSOR_SIZE): int32 index carry
        # would silently truncate — run the gather under x64
        from ..base import x64_scope

        with x64_scope(True):
            return jnp.take(a, indices.astype(jnp.int64), axis=axis,
                            mode=m)
    return jnp.take(a, indices.astype(jnp.int32), axis=axis, mode=m)


@register("batch_take")
def batch_take(a, indices):
    return jnp.take_along_axis(
        a, indices.astype(jnp.int32)[..., None], axis=-1
    ).squeeze(-1)


@register("pick")
def pick(data, index, axis=-1, keepdims=False, mode="clip"):
    idx = jnp.expand_dims(index.astype(jnp.int32), axis=axis)
    out = jnp.take_along_axis(data, idx, axis=axis)
    return out if keepdims else jnp.squeeze(out, axis=axis)


@register("one_hot")
def one_hot(indices, depth=1, on_value=1.0, off_value=0.0, dtype="float32"):
    oh = jnp.asarray(
        indices.astype(jnp.int32)[..., None] == jnp.arange(depth))
    return jnp.where(oh, on_value, off_value).astype(np_dtype(dtype))


@register("Embedding", aliases=("embedding",))
def embedding(data, weight, input_dim=None, output_dim=None, dtype="float32",
              sparse_grad=False):
    # Gather rows of the table; on TPU this is a dynamic-gather the compiler
    # handles well.  Under jit, sparse_grad needs no special handling: XLA's
    # scatter-add transpose of the gather IS the fused row update.  The
    # eager compact-gradient path (O(touched rows) buffers) lives in
    # sparse_embedding below.
    return jnp.take(weight, data.astype(jnp.int32), axis=0, mode="clip")


def sparse_embedding(data, weight):
    """Eager Embedding whose weight gradient is a compact row-sparse
    cotangent — O(touched rows) device memory, the reference's
    sparse_grad=True path (src/operator/tensor/indexing_op.cc backward
    with req=kWriteTo on a row_sparse grad).

    data/weight: NDArrays.  Must run outside jit (the tape is eager by
    definition); inside jit the dense path above is already optimal.
    """
    from .. import autograd as _ag
    from ..ndarray.ndarray import _from_jax
    from ..ndarray.sparse import _RowSparseCt

    class _Fn(_ag.Function):
        def forward(self, data, weight):
            self._wshape = tuple(weight.shape)
            self._wdtype = weight._data.dtype
            # clip ONCE and reuse in backward: scattering at raw ids
            # would misroute out-of-range gradients (e.g. -1 lands on
            # the last row) while the forward read the clamped row
            self._ids = jnp.clip(data._data.astype(jnp.int32), 0,
                                 self._wshape[0] - 1)
            return _from_jax(jnp.take(weight._data, self._ids, axis=0))

        def backward(self, g):
            import jax

            ids = self._ids.reshape(-1)
            cols = self._wshape[1:]
            gv = g._data.reshape((-1,) + cols)
            # coalesce at the op so downstream accumulation stays small
            uniq, inv = jnp.unique(ids, return_inverse=True)
            vals = jax.ops.segment_sum(
                gv.astype(jnp.float32), inv.reshape(-1),
                num_segments=uniq.shape[0]).astype(self._wdtype)
            return None, _RowSparseCt(uniq, vals, self._wshape)

    return _Fn()(data, weight)


@register("gather_nd")
def gather_nd(data, indices):
    idx = tuple(indices.astype(jnp.int32))
    return data[idx]


@register("scatter_nd")
def scatter_nd(data, indices, shape=None):
    out = jnp.zeros(tuple(shape), dtype=data.dtype)
    idx = tuple(indices.astype(jnp.int32))
    return out.at[idx].set(data)


@register("_contrib_index_copy", aliases=("index_copy",))
def index_copy(old, index, new):
    """Reference: contrib/index_copy.cc (out-of-place here — the
    reference mutates via out=)."""
    return old.at[index.astype(jnp.int32)].set(new)


@register("index_add")
def index_add(old, index, new):
    return old.at[index.astype(jnp.int32)].add(new)


@register("_contrib_boolean_mask", aliases=("boolean_mask",))
def boolean_mask(data, index, axis=0):
    # Dynamic-shape op in the reference (src/operator/contrib/boolean_mask.cc).
    # XLA needs static shapes: we keep full size and compact valid rows to the
    # front, returning (masked_data, valid_count)-style padded output is not
    # API-compatible, so eager-only via host fallback.
    import numpy as np

    mask = np.asarray(index).astype(bool)
    return jnp.compress(mask, data, axis=axis)


@register("sequence_mask", aliases=("SequenceMask",))
def sequence_mask(data, sequence_length=None, use_sequence_length=False,
                  value=0.0, axis=0):
    if not use_sequence_length or sequence_length is None:
        return data
    maxlen = data.shape[axis]
    steps = jnp.arange(maxlen)
    # sequence axis is `axis` (0 or 1), batch is the other of the first two.
    batch_axis = 1 - axis
    mask = steps[:, None] < sequence_length[None, :]  # (seq, batch)
    if axis == 1:
        mask = mask.T
    shape = [1] * data.ndim
    shape[axis] = data.shape[axis]
    shape[batch_axis] = data.shape[batch_axis]
    mask = mask.reshape(shape)
    return jnp.where(mask, data, value)


@register("SequenceLast", aliases=("sequence_last",))
def sequence_last(data, sequence_length=None, use_sequence_length=False,
                  axis=0):
    if not use_sequence_length or sequence_length is None:
        idx = [builtins_slice(None)] * data.ndim
        idx[axis] = -1
        return data[tuple(idx)]
    last = (sequence_length.astype(jnp.int32) - 1)
    moved = jnp.moveaxis(data, axis, 0)  # (seq, batch, ...)
    batch = moved.shape[1]
    return moved[last, jnp.arange(batch)]


def builtins_slice(*a):
    import builtins

    return builtins.slice(*a)


@register("SequenceReverse", aliases=("sequence_reverse",))
def sequence_reverse(data, sequence_length=None, use_sequence_length=False,
                     axis=0):
    if not use_sequence_length or sequence_length is None:
        return jnp.flip(data, axis=axis)
    moved = jnp.moveaxis(data, axis, 0)
    seq = moved.shape[0]
    steps = jnp.arange(seq)[:, None]
    L = sequence_length.astype(jnp.int32)[None, :]
    src = jnp.where(steps < L, L - 1 - steps, steps)  # (seq, batch)
    out = jnp.take_along_axis(
        moved, src.reshape(src.shape + (1,) * (moved.ndim - 2)), axis=0)
    return jnp.moveaxis(out, 0, axis)


@register("unravel_index")
def unravel_index(data, shape=None):
    """Flat index → multi-index rows (reference: tensor/ravel.cc)."""
    idx = jnp.stack(jnp.unravel_index(data.astype(jnp.int32),
                                      tuple(int(s) for s in shape)))
    return idx.astype(data.dtype)


@register("ravel_multi_index")
def ravel_multi_index(data, shape=None):
    """Multi-index rows (N, ...) → flat indices (reference: ravel.cc)."""
    shape = tuple(int(s) for s in shape)
    strides = []
    acc = 1
    for s in reversed(shape):
        strides.append(acc)
        acc *= s
    strides = jnp.asarray(list(reversed(strides)), data.dtype)
    return jnp.sum(data * strides.reshape((-1,) + (1,) * (data.ndim - 1)),
                   axis=0)


@register("_contrib_index_array", aliases=("index_array",))
def index_array(data, axes=None):
    """Per-element coordinate array (reference: contrib/index_array.cc):
    output shape = data.shape + (len(axes),), entry = the element's
    index along each requested axis (default: all axes)."""
    sel = tuple(range(data.ndim)) if axes is None \
        else tuple(int(a) % data.ndim for a in axes)  # negatives OK
    coords = [jnp.broadcast_to(
        jnp.arange(data.shape[a]).reshape(
            (1,) * a + (-1,) + (1,) * (data.ndim - a - 1)),
        data.shape) for a in sel]
    from ..base import x64_scope

    # reference output dtype is int64 — needs the x64 scope or jax's
    # x32 default silently downcasts the astype
    with x64_scope(True):
        return jnp.stack(coords, axis=-1).astype(jnp.int64)


@register("_contrib_allclose", aliases=("allclose",))
def allclose(a, b, rtol=1e-5, atol=1e-8, equal_nan=False):
    """Scalar 1.0/0.0 closeness test (reference: contrib/allclose_op.cc)."""
    return jnp.allclose(a, b, rtol=rtol, atol=atol,
                        equal_nan=bool(equal_nan)).astype(jnp.float32)
