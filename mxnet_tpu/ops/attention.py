"""Attention ops.

Reference parity: src/operator/contrib/transformer.cu (≥1.5 interleaved
self-attention GEMM ops: interleaved_matmul_selfatt_qk / valatt, plus
multi-head attention support ops).  TPU-first: attention is expressed as
einsums XLA maps straight onto the MXU; the sequence-parallel variants
(ring / ulysses, parallel/ring.py) plug in via ``impl=``; the Pallas
flash-attention kernel (ops/pallas_attention.py) takes over for long
sequences on real TPU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register

_NEG = -1e30


@register("scaled_dot_product_attention", random=True,
          mode_dependent=True)
def scaled_dot_product_attention(query, key, value, mask=None,
                                 causal=False, scale=None, impl="dense",
                                 dropout_p=0.0, _key=None,
                                 _is_training=True):
    """q,k,v: (B, H, T, D).  mask: broadcastable to (B, H, Tq, Tk), 1=keep.

    impl: 'dense' | 'ring' | 'ulysses' | 'flash' (flash falls back to dense
    off-TPU).  mask/dropout are dense-path features; the sharded/fused
    impls reject them loudly instead of silently ignoring them.
    """
    if scale is None:
        scale = query.shape[-1] ** -0.5
    if impl != "dense" and (mask is not None or dropout_p > 0.0):
        raise NotImplementedError(
            f"attention impl={impl!r} supports only causal masking; "
            "explicit masks / attention dropout require impl='dense'")
    if impl == "ring":
        from ..parallel.ring import ring_attention

        return ring_attention(query, key, value, causal=causal,
                              scale=scale)
    if impl == "ulysses":
        from ..parallel.ring import ulysses_attention

        return ulysses_attention(query, key, value, causal=causal,
                                 scale=scale)
    if impl == "flash":
        from .pallas_attention import flash_attention

        return flash_attention(query, key, value, causal=causal,
                               scale=scale)
    s = jnp.einsum("bhqd,bhkd->bhqk", query.astype(jnp.float32),
                   key.astype(jnp.float32)) * scale
    if causal:
        Tq, Tk = s.shape[-2], s.shape[-1]
        cmask = jnp.tril(jnp.ones((Tq, Tk), bool), k=Tk - Tq)
        s = jnp.where(cmask[None, None], s, _NEG)
    if mask is not None:
        s = jnp.where(mask.astype(bool), s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    if dropout_p > 0.0 and _is_training:
        keep = jax.random.bernoulli(_key, 1.0 - dropout_p, p.shape)
        p = jnp.where(keep, p / (1.0 - dropout_p), 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", p,
                      value.astype(jnp.float32)).astype(query.dtype)


def _split_heads(x, num_heads):
    B, T, C = x.shape
    return x.reshape(B, T, num_heads, C // num_heads).transpose(0, 2, 1, 3)


def _merge_heads(x):
    B, H, T, D = x.shape
    return x.transpose(0, 2, 1, 3).reshape(B, T, H * D)


@register("multi_head_attention")
def multi_head_attention(query, key, value, qkv_weight=None, qkv_bias=None,
                         proj_weight=None, proj_bias=None, num_heads=1,
                         mask=None, causal=False, impl="dense"):
    """Full fused MHA on (B, T, C) inputs with packed qkv projection
    (reference: the contrib/transformer interleaved kernels fused exactly
    this to avoid three GEMMs — one packed MXU matmul here)."""
    if qkv_weight is not None:
        if query is key and key is value:
            qkv = jnp.einsum("btc,gc->btg", query, qkv_weight)
            if qkv_bias is not None:
                qkv = qkv + qkv_bias
            q, k, v = jnp.split(qkv, 3, axis=-1)
        else:
            wq, wk, wv = jnp.split(qkv_weight, 3, axis=0)
            bq = bk = bv = None
            if qkv_bias is not None:
                bq, bk, bv = jnp.split(qkv_bias, 3, axis=0)
            q = jnp.einsum("btc,gc->btg", query, wq)
            k = jnp.einsum("btc,gc->btg", key, wk)
            v = jnp.einsum("btc,gc->btg", value, wv)
            if bq is not None:
                q, k, v = q + bq, k + bk, v + bv
    else:
        q, k, v = query, key, value
    qh = _split_heads(q, num_heads)
    kh = _split_heads(k, num_heads)
    vh = _split_heads(v, num_heads)
    out = scaled_dot_product_attention(qh, kh, vh, mask=mask,
                                       causal=causal, impl=impl)
    out = _merge_heads(out)
    if proj_weight is not None:
        out = jnp.einsum("btg,cg->btc", out, proj_weight)
        if proj_bias is not None:
            out = out + proj_bias
    return out


# reference contrib op names (src/operator/contrib/transformer.cu): the
# interleaved projections as explicit ops for API parity
@register("_contrib_interleaved_matmul_selfatt_qk",
          aliases=("interleaved_matmul_selfatt_qk",))
def interleaved_matmul_selfatt_qk(queries_keys_values, heads=1):
    """Input (T, B, 3C) interleaved qkv → scores (B*heads, T, T)."""
    T, B, C3 = queries_keys_values.shape
    C = C3 // 3
    x = queries_keys_values.reshape(T, B, heads, 3 * (C // heads))
    q, k, _ = jnp.split(x, 3, axis=-1)
    q = q.transpose(1, 2, 0, 3).reshape(B * heads, T, C // heads)
    k = k.transpose(1, 2, 0, 3).reshape(B * heads, T, C // heads)
    scale = (C // heads) ** -0.5
    return jnp.einsum("nqd,nkd->nqk", q, k) * scale


@register("_contrib_interleaved_matmul_selfatt_valatt",
          aliases=("interleaved_matmul_selfatt_valatt",))
def interleaved_matmul_selfatt_valatt(queries_keys_values, attention,
                                      heads=1):
    """attention (B*heads, T, T) × interleaved values → (T, B, C)."""
    T, B, C3 = queries_keys_values.shape
    C = C3 // 3
    x = queries_keys_values.reshape(T, B, heads, 3 * (C // heads))
    _, _, v = jnp.split(x, 3, axis=-1)
    v = v.transpose(1, 2, 0, 3).reshape(B * heads, T, C // heads)
    out = jnp.einsum("nqk,nkd->nqd", attention, v)
    out = out.reshape(B, heads, T, C // heads).transpose(2, 0, 1, 3)
    return out.reshape(T, B, C)


@register("scan_transformer_encoder", mode_dependent=True, random=True)
def scan_transformer_encoder(data, qkv_w, qkv_b, proj_w, proj_b,
                             ffn1_w, ffn1_b, ffn2_w, ffn2_b,
                             ln1_g, ln1_b, ln2_g, ln2_b, lnf_g, lnf_b,
                             qkv_lora_a=None, qkv_lora_b=None,
                             num_heads=1, dropout=0.0,
                             activation="gelu", impl="dense",
                             causal=False, remat=False, lora_scale=1.0,
                             _is_training=True, _key=None):
    """Pre-LN transformer trunk as ONE lax.scan over stacked (L, ...)
    per-layer parameters.

    TPU-first compile-time scalability: N separate layer blocks emit an
    HLO that grows linearly with depth (a BERT-base whole-step compile
    through the AOT helper takes tens of minutes); scanning one layer
    body over parameter stacks compiles the layer once.  Same math as
    gluon's TransformerEncoder (packed-qkv MHA + pre-LN FFN),
    equivalence-tested in tests/test_model_zoo.py.

    LoRA fine-tuning (Hu et al. 2021, beyond reference): optional
    ``qkv_lora_a`` (L, r, U) / ``qkv_lora_b`` (L, 3U, r) stacks add a
    rank-r update to each layer's packed qkv weight — the effective
    weight ``qkv + lora_scale·(B@A)`` is formed per scan step (one
    (3U,r)x(r,U) matmul, transient), so the trunk stays ONE scanned
    layer and the adapters train through the product while the base
    stacks stay frozen (grad_req='null').
    """
    from .nn import layer_norm

    use_drop = bool(dropout) and _is_training
    use_lora = qkv_lora_a is not None and qkv_lora_b is not None
    L = qkv_w.shape[0]

    def body(carry, per_layer):
        (qw, qb, pw, pb, f1w, f1b, f2w, f2b, g1, b1, g2, b2) = \
            per_layer[:12]
        rest = list(per_layer[12:])
        if use_lora:
            la, lb = rest[0], rest[1]
            rest = rest[2:]
            qw = (qw + lora_scale * jnp.matmul(
                lb, la, preferred_element_type=jnp.float32)
                .astype(qw.dtype))
        key = rest[0] if use_drop else None
        x = carry
        h = layer_norm(x, g1, b1)
        attn = multi_head_attention(
            h, h, h, qkv_weight=qw, qkv_bias=qb, proj_weight=pw,
            proj_bias=pb, num_heads=num_heads, impl=impl,
            causal=causal)
        if use_drop:
            k1, k2 = jax.random.split(key)
            keep = 1.0 - dropout
            attn = jnp.where(
                jax.random.bernoulli(k1, keep, attn.shape),
                attn / keep, 0.0).astype(attn.dtype)
        x = x + attn
        h = layer_norm(x, g2, b2)
        h = jnp.einsum("btc,hc->bth", h, f1w,
                       preferred_element_type=jnp.float32) \
            .astype(x.dtype) + f1b
        h = jax.nn.gelu(h) if activation == "gelu" \
            else jnp.maximum(h, 0)
        h = (jnp.einsum("bth,ch->btc", h, f2w,
                        preferred_element_type=jnp.float32)
             .astype(x.dtype) + f2b)
        if use_drop:
            h = jnp.where(jax.random.bernoulli(k2, keep, h.shape),
                          h / keep, 0.0).astype(h.dtype)
        return x + h, None

    xs = (qkv_w, qkv_b, proj_w, proj_b, ffn1_w, ffn1_b, ffn2_w,
          ffn2_b, ln1_g, ln1_b, ln2_g, ln2_b)
    if use_lora:
        xs = xs + (qkv_lora_a, qkv_lora_b)
    if use_drop:
        xs = xs + (jax.random.split(_key, L),)
    from .. import remat as _remat

    pol = _remat.trunk_policy(remat)
    every = pol[1] if pol is not None and pol[0] == "every" else None
    if every is not None and (L % every != 0 or every == 1):
        # non-divisible chunking would need a ragged tail scan;
        # degrade to per-layer (every=1 IS per-layer)
        pol = ("layer", None)
        every = None
    if every is not None:
        # chunked rematerialization (remat.py 'save_every_k:N'): scan
        # L/N checkpointed chunks of N layers each — the backward keeps
        # only chunk-boundary carries resident and recomputes inside a
        # chunk.  The inner scan runs the SAME body on the same values
        # as the flat scan, so the math is bitwise-unchanged.
        def chunk(carry, per_chunk):
            out, _ = jax.lax.scan(body, carry, per_chunk)
            return out, None

        chunk = jax.checkpoint(chunk)
        xs = tuple(x.reshape((L // every, every) + x.shape[1:])
                   for x in xs)
        out, _ = jax.lax.scan(chunk, data, xs)
        return layer_norm(out, lnf_g, lnf_b)
    if pol is not None:
        # per-layer rematerialization: the backward recomputes each
        # layer's activations from its carry — O(1) layers of
        # activations resident instead of O(L) (the long-context knob;
        # composes with the reference's MXNET_BACKWARD_DO_MIRROR story)
        body = jax.checkpoint(body, policy=pol[1])
    out, _ = jax.lax.scan(body, data, xs)
    return layer_norm(out, lnf_g, lnf_b)
