"""Flash attention as a Pallas TPU kernel — forward AND backward.

Reference parity target: the fused MHA kernels the reference gets from
contrib/transformer.cu + cuDNN; here the TPU version is a blockwise
online-softmax kernel (Flash-Attention) so the (Tq × Tk) score matrix never
materializes in HBM:

- grid over (batch·heads, Tq blocks); K/V stream through VMEM in Tk blocks
  inside a fori_loop;
- the score block Q·Kᵀ runs on the MXU with f32 accumulation;
- m/l/o accumulators live in VMEM scratch across the inner loop;
- causal masking skips fully-masked KV blocks (upper-triangle blocks are
  never even loaded — the index map keeps them out of the loop bound);
- the forward also emits the per-row logsumexp L = m + log(l), and the
  backward is the FlashAttention-2 recipe: recompute the probability
  block p = exp(s − L) per tile and accumulate dq (one kernel, grid over
  q blocks) and dk/dv (one kernel, grid over kv blocks) in VMEM — no
  O(T²) HBM tensor in training either.

Off-TPU (tests, CPU mesh) the kernels run in interpret mode, keeping one
code path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_NEG = -1e30
_LANE = 128


def _use_interpret():
    return jax.default_backend() != "tpu"


def _block_sizes(T):
    block_q = min(max(_LANE, 1), T)
    while T % block_q:
        block_q //= 2
    block_k = min(_LANE, T)
    while T % block_k:
        block_k //= 2
    return block_q, block_k


# -- forward -------------------------------------------------------------------

def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k,
                      causal, scale, q_block, seq_len):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)  # (Bq, D)
    Bq, D = q.shape
    nkb = pl.cdiv(seq_len, block_k)
    if causal:
        # block row qi attends kv blocks with start <= q_end
        q_end = (qi + 1) * q_block - 1
        nkb = jnp.minimum(nkb, (q_end // block_k) + 1)

    def body(j, carry):
        o, l, m = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (Bq, Bk)
        if causal:
            qpos = qi * q_block + jax.lax.broadcasted_iota(
                jnp.int32, (Bq, block_k), 0)
            kpos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (Bq, block_k), 1)
            s = jnp.where(qpos >= kpos, s, _NEG)
        m_blk = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m, m_blk)
        p = jnp.exp(s - m_new[:, None])
        p = jnp.where(s <= _NEG / 2, 0.0, p)
        alpha = jnp.exp(m - m_new)
        alpha = jnp.where(m <= _NEG / 2, 0.0, alpha)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        o_new = o * alpha[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return o_new, l_new, m_new

    o0 = jnp.zeros((Bq, D), jnp.float32)
    l0 = jnp.zeros((Bq,), jnp.float32)
    m0 = jnp.full((Bq,), _NEG, jnp.float32)
    o, l, m = jax.lax.fori_loop(0, nkb, body, (o0, l0, m0))
    lsafe = jnp.where(l == 0.0, 1.0, l)
    o_ref[0] = (o / lsafe[:, None]).astype(o_ref.dtype)
    lse_ref[0] = m + jnp.log(lsafe)


def _flash_call(q, k, v, causal, scale):
    from jax.experimental import pallas as pl

    B, H, T, D = q.shape
    qr = q.reshape(B * H, T, D)
    kr = k.reshape(B * H, T, D)
    vr = v.reshape(B * H, T, D)
    block_q, block_k = _block_sizes(T)
    grid = (B * H, T // block_q)
    kernel = functools.partial(
        _flash_fwd_kernel, block_k=block_k, causal=causal, scale=scale,
        q_block=block_q, seq_len=T)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, T, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, T, D), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q), lambda b, i: (b, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, T), jnp.float32),
        ],
        interpret=_use_interpret(),
    )(qr, kr, vr)
    return out.reshape(B, H, T, D), lse.reshape(B, H, T)


# -- backward (FlashAttention-2) -----------------------------------------------

def _flash_dq_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
                     dq_ref, *, block_k, causal, scale, q_block, seq_len):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)      # (Bq, D)
    g = g_ref[0].astype(jnp.float32)      # (Bq, D)
    lse = lse_ref[0]                      # (Bq,)
    delta = delta_ref[0]                  # (Bq,)
    Bq, D = q.shape
    nkb = pl.cdiv(seq_len, block_k)
    if causal:
        q_end = (qi + 1) * q_block - 1
        nkb = jnp.minimum(nkb, (q_end // block_k) + 1)

    def body(j, dq):
        k = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            qpos = qi * q_block + jax.lax.broadcasted_iota(
                jnp.int32, (Bq, block_k), 0)
            kpos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (Bq, block_k), 1)
            s = jnp.where(qpos >= kpos, s, _NEG)
        p = jnp.exp(s - lse[:, None])
        p = jnp.where(s <= _NEG / 2, 0.0, p)
        dp = jax.lax.dot_general(                      # dO · Vᵀ
            g, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        return dq + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(0, nkb, body, jnp.zeros((Bq, D), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _flash_dkv_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
                      dk_ref, dv_ref, *, block_q, causal, scale, k_block,
                      seq_len):
    from jax.experimental import pallas as pl

    ki = pl.program_id(1)
    k = k_ref[0].astype(jnp.float32)      # (Bk, D)
    v = v_ref[0].astype(jnp.float32)      # (Bk, D)
    Bk, D = k.shape
    nqb = pl.cdiv(seq_len, block_q)
    # causal: q block rows strictly above this kv block are fully masked
    start = (ki * k_block) // block_q if causal else 0

    def body(i, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        g = g_ref[0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(i * block_q, block_q)]
        delta = delta_ref[0, pl.ds(i * block_q, block_q)]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (Bq, Bk)
        if causal:
            qpos = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, Bk), 0)
            kpos = ki * k_block + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, Bk), 1)
            s = jnp.where(qpos >= kpos, s, _NEG)
        p = jnp.exp(s - lse[:, None])
        p = jnp.where(s <= _NEG / 2, 0.0, p)
        dv = dv + jax.lax.dot_general(                  # Pᵀ · dO
            p, g, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            g, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        dk = dk + jax.lax.dot_general(                  # dSᵀ · Q
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk, dv

    z = jnp.zeros((Bk, D), jnp.float32)
    dk, dv = jax.lax.fori_loop(start, nqb, body, (z, z))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_bwd_call(q, k, v, out, lse, g, causal, scale):
    from jax.experimental import pallas as pl

    B, H, T, D = q.shape
    qr = q.reshape(B * H, T, D)
    kr = k.reshape(B * H, T, D)
    vr = v.reshape(B * H, T, D)
    gr = g.reshape(B * H, T, D)
    lser = lse.reshape(B * H, T)
    # D_i = rowsum(dO ∘ O) — tiny, XLA fuses it
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1).reshape(B * H, T)
    block_q, block_k = _block_sizes(T)
    interpret = _use_interpret()

    dq_kernel = functools.partial(
        _flash_dq_kernel, block_k=block_k, causal=causal, scale=scale,
        q_block=block_q, seq_len=T)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(B * H, T // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, T, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, T, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q), lambda b, i: (b, i)),
            pl.BlockSpec((1, block_q), lambda b, i: (b, i)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
        interpret=interpret,
    )(qr, kr, vr, gr, lser, delta)

    dkv_kernel = functools.partial(
        _flash_dkv_kernel, block_q=block_q, causal=causal, scale=scale,
        k_block=block_k, seq_len=T)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(B * H, T // block_k),
        in_specs=[
            pl.BlockSpec((1, T, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, T, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, T), lambda b, i: (b, 0)),
            pl.BlockSpec((1, T), lambda b, i: (b, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, T, D), k.dtype),
            jax.ShapeDtypeStruct((B * H, T, D), v.dtype),
        ],
        interpret=interpret,
    )(qr, kr, vr, gr, lser, delta)

    return (dq.reshape(B, H, T, D), dk.reshape(B, H, T, D),
            dv.reshape(B, H, T, D))


# -- custom vjp ----------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_core(q, k, v, causal, scale):
    out, _ = _flash_call(q, k, v, causal, scale)
    return out


def _dense_ref(q, k, v, causal, scale):
    """Dense oracle for tests (and the doc of what the kernel computes)."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        T = s.shape[-1]
        mask = jnp.tril(jnp.ones((s.shape[-2], T), bool), k=T - s.shape[-2])
        s = jnp.where(mask[None, None], s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


def _flash_fwd(q, k, v, causal, scale):
    out, lse = _flash_call(q, k, v, causal, scale)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, scale, res, g):
    q, k, v, out, lse = res
    return _flash_bwd_call(q, k, v, out, lse, g, causal, scale)


_flash_core.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, causal=False, scale=None):
    """Blockwise fused attention; q,k,v: (B, H, T, D)."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    return _flash_core(q, k, v, bool(causal), float(scale))
