"""Flash attention as a Pallas TPU kernel.

Reference parity target: the fused MHA kernels the reference gets from
contrib/transformer.cu + cuDNN; here the TPU version is a blockwise
online-softmax kernel (Flash-Attention) so the (Tq × Tk) score matrix never
materializes in HBM:

- grid over (batch·heads, Tq blocks); K/V stream through VMEM in Tk blocks
  inside a fori_loop;
- the score block Q·Kᵀ runs on the MXU with f32 accumulation;
- m/l/o accumulators live in VMEM scratch across the inner loop;
- causal masking skips fully-masked KV blocks (upper-triangle blocks are
  never even loaded — the index map keeps them out of the loop bound).

Off-TPU (tests, CPU mesh) the kernel runs in interpret mode, keeping one
code path.  Backward currently flows through ``jax.custom_vjp`` with a
recompute-based pullback built on the same kernel primitives.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_NEG = -1e30
_LANE = 128


def _use_interpret():
    return jax.default_backend() != "tpu"


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k, causal,
                      scale, q_block, seq_len):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)  # (Bq, D)
    Bq, D = q.shape
    nkb = pl.cdiv(seq_len, block_k)
    if causal:
        # block row qi attends kv blocks with start <= q_end
        q_end = (qi + 1) * q_block - 1
        nkb = jnp.minimum(nkb, (q_end // block_k) + 1)

    def body(j, carry):
        o, l, m = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (Bq, Bk)
        if causal:
            qpos = qi * q_block + jax.lax.broadcasted_iota(
                jnp.int32, (Bq, block_k), 0)
            kpos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (Bq, block_k), 1)
            s = jnp.where(qpos >= kpos, s, _NEG)
        m_blk = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m, m_blk)
        p = jnp.exp(s - m_new[:, None])
        p = jnp.where(s <= _NEG / 2, 0.0, p)
        alpha = jnp.exp(m - m_new)
        alpha = jnp.where(m <= _NEG / 2, 0.0, alpha)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        o_new = o * alpha[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return o_new, l_new, m_new

    o0 = jnp.zeros((Bq, D), jnp.float32)
    l0 = jnp.zeros((Bq,), jnp.float32)
    m0 = jnp.full((Bq,), _NEG, jnp.float32)
    o, l, m = jax.lax.fori_loop(0, nkb, body, (o0, l0, m0))
    l = jnp.where(l == 0.0, 1.0, l)
    o_ref[0] = (o / l[:, None]).astype(o_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_core(q, k, v, causal, scale):
    return _flash_call(q, k, v, causal, scale)


def _flash_call(q, k, v, causal, scale):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, T, D = q.shape
    qr = q.reshape(B * H, T, D)
    kr = k.reshape(B * H, T, D)
    vr = v.reshape(B * H, T, D)
    block_q = min(max(_LANE, 1), T)
    while T % block_q:
        block_q //= 2
    block_k = min(_LANE, T)
    while T % block_k:
        block_k //= 2
    grid = (B * H, T // block_q)
    kernel = functools.partial(
        _flash_fwd_kernel, block_k=block_k, causal=causal, scale=scale,
        q_block=block_q, seq_len=T)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, T, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, T, D), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
        interpret=_use_interpret(),
    )(qr, kr, vr)
    return out.reshape(B, H, T, D)


def _dense_ref(q, k, v, causal, scale):
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        T = s.shape[-1]
        mask = jnp.tril(jnp.ones((s.shape[-2], T), bool), k=T - s.shape[-2])
        s = jnp.where(mask[None, None], s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


def _flash_fwd(q, k, v, causal, scale):
    return _flash_call(q, k, v, causal, scale), (q, k, v)


def _flash_bwd(causal, scale, res, g):
    # recompute-based backward through the dense reference: numerically
    # identical gradients; a blockwise Pallas backward is the planned
    # optimization (forward dominates inference; training long-context
    # uses ring attention whose scan JAX transposes natively)
    q, k, v = res
    _, vjp = jax.vjp(lambda q, k, v: _dense_ref(q, k, v, causal, scale),
                     q, k, v)
    return vjp(g)


_flash_core.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, causal=False, scale=None):
    """Blockwise fused attention; q,k,v: (B, H, T, D)."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    return _flash_core(q, k, v, bool(causal), float(scale))
