"""Flash attention as a Pallas TPU kernel — forward AND backward.

Reference parity target: the fused MHA kernels the reference gets from
contrib/transformer.cu + cuDNN; here the TPU version is a blockwise
online-softmax kernel (Flash-Attention-2) so neither the (Tq × Tk) score
matrix nor the whole K/V sequence is ever resident:

- grid (batch·heads, q blocks, kv blocks): K and V stream through VMEM
  one (block_k, D) tile per grid step — per-step VMEM is bounded by the
  block sizes and INDEPENDENT of sequence length (long-context safe);
- the score block Q·Kᵀ runs on the MXU with f32 accumulation;
- m/l/o accumulators live in VMEM scratch, carried across the kv grid
  dimension ("arbitrary" semantics); outputs store on the last kv step;
- m/l are kept lane-replicated (block_q, 128) in VMEM so the
  online-softmax update is pure elementwise VPU work — the same layout
  trick the production TPU kernels use; the logsumexp persisted to HBM
  for the backward is narrowed to (B·H, T, 8) (the minimum Mosaic-legal
  lane tile) and re-broadcast from lane 0 inside the bwd kernels;
- causal q/kv block pairs above the diagonal skip all compute (pl.when);
- backward is the FlashAttention-2 recipe: recompute p = exp(s − L) per
  tile; dq accumulates over the kv grid, dk/dv over the q grid; D_i =
  rowsum(dO ∘ O) is computed in-kernel from the O/dO tiles (never
  materialized in HBM).

Off-TPU (tests, CPU mesh) the kernels run in interpret mode, keeping one
code path.  On TPU, sequence lengths not divisible by 128 fall back to a
dense XLA path (flash only matters at lengths where T % 128 == 0 is
free to arrange).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_NEG = -1e30
_LANE = 128


def _use_interpret():
    return jax.default_backend() != "tpu"


def _block_sizes(T):
    if T % _LANE == 0:
        # bq capped at 256: the dq backward's f32 working set at bq=512
        # (dq scratch + (bq,bk) intermediates + double-buffered operand
        # blocks) blows the ~16MB scoped-VMEM budget at BERT shapes
        # (measured: b32·h12·T512·D64 fails to compile at 512, fits at
        # 256)
        bq = 256 if T % 256 == 0 else _LANE
        return min(bq, T), _LANE
    # interpret-mode small/odd shapes; real TPU dispatches dense instead
    return T, T


# lanes of logsumexp/delta actually persisted to HBM between fwd and bwd
# (sublane-legal minimum; ×8 instead of the kernels' working ×128)
_LSE_LANES = 8


def _bcast_lanes(x, n):
    """lane-replicated (bq, k) -> (bq, n); every lane of x is identical."""
    k = x.shape[1]
    if n == k:
        return x
    if n < k:
        return x[:, :n]
    if n % k == 0:
        return jnp.tile(x, (1, n // k))
    return jnp.broadcast_to(x[:, :1], (x.shape[0], n))


# -- forward -------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr,
                acc_scr, *, scale, causal, block_q, block_k, nk):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    kj = pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        m_scr[...] = jnp.full(m_scr.shape, _NEG, jnp.float32)
        l_scr[...] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[...] = jnp.zeros(acc_scr.shape, jnp.float32)

    def _run():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (bq, bk)
        if causal:
            qpos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0)
            kpos = kj * block_k + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1)
            s = jnp.where(qpos >= kpos, s, _NEG)
        m_prev = m_scr[...]
        l_prev = l_scr[...]
        m_curr = jnp.max(s, axis=1)[:, None]          # (bq, 1)
        m_next = jnp.maximum(m_prev, m_curr)          # (bq, 128)
        p = jnp.exp(s - _bcast_lanes(m_next, s.shape[1]))
        p = jnp.where(s <= _NEG / 2, 0.0, p)
        alpha = jnp.exp(m_prev - m_next)
        l_scr[...] = alpha * l_prev + jnp.sum(p, axis=1)[:, None]
        m_scr[...] = m_next
        v = v_ref[0]
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        D = acc_scr.shape[1]
        acc_scr[...] = acc_scr[...] * _bcast_lanes(alpha, D) + pv

    if causal:
        pl.when(kj * block_k <= (qi + 1) * block_q - 1)(_run)
    else:
        _run()

    @pl.when(kj == nk - 1)
    def _store():
        l = l_scr[...]
        lsafe = jnp.where(l == 0.0, 1.0, l)
        D = acc_scr.shape[1]
        o_ref[0] = (acc_scr[...] / _bcast_lanes(lsafe, D)).astype(
            o_ref.dtype)
        lse_ref[0] = (m_scr[...] + jnp.log(lsafe))[:, :_LSE_LANES]


def _sds(shape, dtype, vma):
    """ShapeDtypeStruct that carries the varying-mesh-axes set when the
    kernel runs inside a check_vma=True shard_map (ring attention)."""
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=frozenset(vma))
    return jax.ShapeDtypeStruct(shape, dtype)


def _flash_call(q, k, v, causal, scale, block_q, block_k, vma=None):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, T, D = q.shape
    qr = q.reshape(B * H, T, D)
    kr = k.reshape(B * H, T, D)
    vr = v.reshape(B * H, T, D)
    nq, nk = T // block_q, T // block_k
    interpret = _use_interpret()
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k, nk=nk)
    kw = {} if interpret else {
        "compiler_params": pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"))}
    out, lse = pl.pallas_call(
        kernel,
        grid=(B * H, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LSE_LANES),
                         lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            _sds((B * H, T, D), q.dtype, vma),
            # logsumexp, ×8 sublane-replicated (narrowest Mosaic-legal
            # lane tile — ×128 would cost 16× the HBM for no information)
            _sds((B * H, T, _LSE_LANES), jnp.float32, vma),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANE), jnp.float32),
            pltpu.VMEM((block_q, _LANE), jnp.float32),
            pltpu.VMEM((block_q, D), jnp.float32),
        ],
        interpret=interpret,
        **kw,
    )(qr, kr, vr)
    return out.reshape(B, H, T, D), lse


# -- backward (FlashAttention-2) -----------------------------------------------

def _dq_kernel(q_ref, k_ref, v_ref, g_ref, o_ref, lse_ref, dq_ref,
               acc_scr, delta_scr, *, scale, causal, block_q, block_k, nk):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    kj = pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        acc_scr[...] = jnp.zeros(acc_scr.shape, jnp.float32)
        g = g_ref[0].astype(jnp.float32)
        o = o_ref[0].astype(jnp.float32)
        delta_scr[...] = jnp.sum(g * o, axis=1)[:, None] * jnp.ones(
            (1, _LANE), jnp.float32)

    def _run():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        g = g_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            qpos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0)
            kpos = kj * block_k + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1)
            s = jnp.where(qpos >= kpos, s, _NEG)
        bk = s.shape[1]
        p = jnp.exp(s - _bcast_lanes(lse_ref[0][:, :1], bk))
        p = jnp.where(s <= _NEG / 2, 0.0, p)
        v = v_ref[0].astype(jnp.float32)
        dp = jax.lax.dot_general(                      # dO · Vᵀ
            g, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - _bcast_lanes(delta_scr[...], bk)) * scale
        acc_scr[...] += jax.lax.dot_general(
            ds.astype(k_ref.dtype), k_ref[0],
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        pl.when(kj * block_k <= (qi + 1) * block_q - 1)(_run)
    else:
        _run()

    @pl.when(kj == nk - 1)
    def _store():
        dq_ref[0] = acc_scr[...].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, g_ref, o_ref, lse_ref, dk_ref,
                dv_ref, dk_scr, dv_scr, *, scale, causal, block_q,
                block_k, nq):
    from jax.experimental import pallas as pl

    ki = pl.program_id(1)
    qj = pl.program_id(2)

    @pl.when(qj == 0)
    def _init():
        dk_scr[...] = jnp.zeros(dk_scr.shape, jnp.float32)
        dv_scr[...] = jnp.zeros(dv_scr.shape, jnp.float32)

    def _run():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        g = g_ref[0].astype(jnp.float32)
        o = o_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (bq, bk)
        if causal:
            qpos = qj * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0)
            kpos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1)
            s = jnp.where(qpos >= kpos, s, _NEG)
        bk = s.shape[1]
        p = jnp.exp(s - _bcast_lanes(lse_ref[0][:, :1], bk))
        p = jnp.where(s <= _NEG / 2, 0.0, p)
        delta = jnp.sum(g * o, axis=1)[:, None]        # (bq, 1)
        v = v_ref[0].astype(jnp.float32)
        dp = jax.lax.dot_general(
            g, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dv_scr[...] += jax.lax.dot_general(            # Pᵀ · dO
            p.astype(g_ref.dtype), g_ref[0],
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dk_scr[...] += jax.lax.dot_general(            # dSᵀ · Q
            ds.astype(q_ref.dtype), q_ref[0],
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        pl.when((qj + 1) * block_q - 1 >= ki * block_k)(_run)
    else:
        _run()

    @pl.when(qj == nq - 1)
    def _store():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _flash_bwd_call(q, k, v, out, lse, g, causal, scale, block_q,
                    block_k, vma=None):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, T, D = q.shape
    qr = q.reshape(B * H, T, D)
    kr = k.reshape(B * H, T, D)
    vr = v.reshape(B * H, T, D)
    gr = g.reshape(B * H, T, D)
    outr = out.reshape(B * H, T, D)
    nq, nk = T // block_q, T // block_k
    interpret = _use_interpret()
    kw = {} if interpret else {
        "compiler_params": pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"))}

    qspec = pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0))
    kspec = pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0))
    lspec = pl.BlockSpec((1, block_q, _LSE_LANES),
                         lambda b, i, j: (b, i, 0))
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, nk=nk),
        grid=(B * H, nq, nk),
        in_specs=[qspec, kspec, kspec, qspec, qspec, lspec],
        out_specs=qspec,
        out_shape=_sds((B * H, T, D), q.dtype, vma),
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),
            pltpu.VMEM((block_q, _LANE), jnp.float32),
        ],
        interpret=interpret,
        **kw,
    )(qr, kr, vr, gr, outr, lse)

    # dkv grid: kv block is the revisited (outer) axis, q streams inner
    qspec2 = pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, j, 0))
    kspec2 = pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, i, 0))
    lspec2 = pl.BlockSpec((1, block_q, _LSE_LANES),
                          lambda b, i, j: (b, j, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, nq=nq),
        grid=(B * H, nk, nq),
        in_specs=[qspec2, kspec2, kspec2, qspec2, qspec2, lspec2],
        out_specs=[kspec2, kspec2],
        out_shape=[
            _sds((B * H, T, D), k.dtype, vma),
            _sds((B * H, T, D), v.dtype, vma),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        interpret=interpret,
        **kw,
    )(qr, kr, vr, gr, outr, lse)

    return (dq.reshape(B, H, T, D), dk.reshape(B, H, T, D),
            dv.reshape(B, H, T, D))


# -- custom vjp ----------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_core(q, k, v, causal, scale, block_q, block_k, vma=()):
    out, _ = _flash_call(q, k, v, causal, scale, block_q, block_k,
                         vma=vma)
    return out


def _dense_ref(q, k, v, causal, scale):
    """Dense oracle for tests, and the TPU path for T % 128 != 0 (and
    the doc of what the kernel computes)."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        T = s.shape[-1]
        mask = jnp.tril(jnp.ones((s.shape[-2], T), bool), k=T - s.shape[-2])
        s = jnp.where(mask[None, None], s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, vma=()):
    out, lse = _flash_call(q, k, v, causal, scale, block_q, block_k,
                           vma=vma)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, scale, block_q, block_k, vma, res, g):
    q, k, v, out, lse = res
    return _flash_bwd_call(q, k, v, out, lse, g, causal, scale, block_q,
                           block_k, vma=vma)


_flash_core.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, causal=False, scale=None, block_q=None,
                    block_k=None, vma=()):
    """Blockwise fused attention; q,k,v: (B, H, T, D).

    ``block_q``/``block_k`` override the tile sizes (tests use small
    blocks to exercise multi-block streaming at modest T).  ``vma``:
    varying-mesh-axes set when calling from inside a check_vma=True
    shard_map region (ring/ulysses)."""
    T = q.shape[2]
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if T % _LANE != 0 and not _use_interpret():
        # TPU lowering needs 128-aligned tiles; short/odd sequences are
        # exactly where dense XLA attention is fine anyway
        return _dense_ref(q, k, v, bool(causal), float(scale))
    dbq, dbk = _block_sizes(T)
    bq, bk = int(block_q or dbq), int(block_k or dbk)
    if T % bq or T % bk:
        raise ValueError(
            f"flash_attention: block sizes ({bq}, {bk}) must divide "
            f"sequence length {T} (a non-dividing block would silently "
            f"leave tail blocks unwritten)")
    return _flash_core(q, k, v, bool(causal), float(scale), bq, bk,
                       tuple(vma))
