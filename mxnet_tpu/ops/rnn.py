"""Fused multi-layer RNN op.

Reference parity: src/operator/rnn.cc + cudnn_rnn-inl.h — the fused
LSTM/GRU/vanilla-RNN kernel behind gluon.rnn layers, with cuDNN's packed
parameter vector layout (all weights layer-major then all biases) and gate
orders (LSTM: i f g o; GRU: r z n).

TPU-first design: per layer, the input projection for the WHOLE sequence is
one big MXU matmul (T·B × in) @ (in × G·H); only the recurrent h @ W_hh
matmul rides inside ``lax.scan``.  Bidirectional runs the reverse direction
as a flipped scan.  Differentiable by construction (JAX transposes the
scan), replacing the hand-written cuDNN backward.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


def rnn_param_size(mode, input_size, state_size, num_layers=1,
                   bidirectional=False, projection_size=None):
    """Total packed parameter count (reference: RNNParam size calc,
    incl. the LSTMP projection rows when projection_size is set)."""
    gates = _GATES[mode]
    dirs = 2 if bidirectional else 1
    P = projection_size
    rec = P if P else state_size          # recurrent/output width
    size = 0
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else rec * dirs
        for _ in range(dirs):
            size += gates * state_size * (in_sz + rec)         # Wx, Wh
            if P:
                size += P * state_size                         # Wr
            size += 2 * gates * state_size                     # bx, bh
    return size


def _unpack_params(params, mode, input_size, state_size, num_layers,
                   dirs, projection_size=None):
    """Split the packed vector into per-layer/direction
    (Wx, Wh[, Wr], bx, bh)."""
    gates = _GATES[mode]
    H = state_size
    P = projection_size
    rec = P if P else H
    weights, biases = [], []
    off = 0
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else rec * dirs
        for _ in range(dirs):
            wx = params[off:off + gates * H * in_sz].reshape(
                gates * H, in_sz)
            off += gates * H * in_sz
            wh = params[off:off + gates * H * rec].reshape(gates * H, rec)
            off += gates * H * rec
            wr = None
            if P:
                wr = params[off:off + P * H].reshape(P, H)
                off += P * H
            weights.append((wx, wh, wr))
    for layer in range(num_layers):
        for _ in range(dirs):
            bx = params[off:off + gates * H]
            off += gates * H
            bh = params[off:off + gates * H]
            off += gates * H
            biases.append((bx, bh))
    return weights, biases


def _cell_step(mode, wr=None):
    if mode == "lstm":
        def step(carry, xproj, wh, bh):
            h, c = carry
            gates = xproj + h @ wh.T + bh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), \
                jax.nn.sigmoid(o)
            g = jnp.tanh(g)
            c = f * c + i * g
            h = o * jnp.tanh(c)
            if wr is not None:  # LSTMP: project the recurrent output
                h = h @ wr.T
            return (h, c), h
        return step
    if mode == "gru":
        def step(carry, xproj, wh, bh):
            (h,) = carry
            hproj = h @ wh.T + bh
            xr, xz, xn = jnp.split(xproj, 3, axis=-1)
            hr, hz, hn = jnp.split(hproj, 3, axis=-1)
            r = jax.nn.sigmoid(xr + hr)
            z = jax.nn.sigmoid(xz + hz)
            n = jnp.tanh(xn + r * hn)
            h = (1.0 - z) * n + z * h
            return (h,), h
        return step
    act = jnp.tanh if mode == "rnn_tanh" else (lambda v: jnp.maximum(v, 0))

    def step(carry, xproj, wh, bh):
        (h,) = carry
        h = act(xproj + h @ wh.T + bh)
        return (h,), h
    return step


def _run_direction(x, h0, c0, wx, wh, bx, bh, mode, reverse, wr=None,
                   seq_len=None):
    """x: (T,B,in) → outputs (T,B,H|P), final (h, c?).

    With ``seq_len`` (B,), steps at t >= len neither update the carry
    nor emit output (reference use_sequence_length masking); the
    reversed direction runs the global flip, so invalid tail steps are
    frozen no-ops and each sequence is effectively reversed within its
    own valid region.
    """
    T = x.shape[0]
    step = _cell_step(mode, wr)
    xproj = jnp.einsum("tbi,gi->tbg", x, wx,
                       preferred_element_type=jnp.float32) \
        .astype(x.dtype) + bx
    ts = jnp.arange(T)
    if reverse:
        xproj = jnp.flip(xproj, axis=0)
        ts = jnp.flip(ts, axis=0)
    carry0 = (h0, c0) if mode == "lstm" else (h0,)

    def scan_fn(carry, inp):
        xp, t = inp
        new_carry, out = step(carry, xp, wh, bh)
        if seq_len is not None:
            valid = (t < seq_len)[:, None]
            new_carry = tuple(
                jnp.where(valid, n, o)
                for n, o in zip(new_carry, carry))
            out = jnp.where(valid, out, jnp.zeros((), out.dtype))
        return new_carry, out

    final, outs = lax.scan(scan_fn, carry0, (xproj, ts))
    if reverse:
        outs = jnp.flip(outs, axis=0)
    return outs, final


@register("RNN", aliases=("rnn",), mode_dependent=True, random=True)
def rnn(data, parameters, state, state_cell=None, state_size=None,
        num_layers=1, mode="lstm", bidirectional=False, p=0.0,
        state_outputs=False, lstm_state_clip_min=None,
        lstm_state_clip_max=None, use_sequence_length=False,
        sequence_length=None, projection_size=None, _is_training=True,
        _key=None):
    """Fused RNN forward.  data: (T, B, input) TNC; state: (L*D, B, H);
    returns output (T, B, H*D) [+ final states when state_outputs]."""
    T, B, input_size = data.shape
    H = state_size
    dirs = 2 if bidirectional else 1
    seq_len = None
    if use_sequence_length:
        if sequence_length is None:
            raise ValueError("use_sequence_length=True needs "
                             "sequence_length (B,)")
        seq_len = sequence_length.astype(jnp.int32)
    weights, biases = _unpack_params(parameters, mode, input_size, H,
                                     num_layers, dirs, projection_size)
    x = data
    h_finals, c_finals = [], []
    key = _key
    for layer in range(num_layers):
        outs_dir = []
        for d in range(dirs):
            idx = layer * dirs + d
            wx, wh, wr = weights[idx]
            bx, bh = biases[idx]
            h0 = state[idx]
            c0 = state_cell[idx] if mode == "lstm" else None
            outs, final = _run_direction(x, h0, c0, wx, wh, bx, bh, mode,
                                         reverse=(d == 1), wr=wr,
                                         seq_len=seq_len)
            outs_dir.append(outs)
            h_finals.append(final[0])
            if mode == "lstm":
                c = final[1]
                if lstm_state_clip_min is not None and \
                        lstm_state_clip_max is not None:
                    c = jnp.clip(c, lstm_state_clip_min,
                                 lstm_state_clip_max)
                c_finals.append(c)
        x = outs_dir[0] if dirs == 1 else jnp.concatenate(outs_dir,
                                                          axis=-1)
        if p > 0 and _is_training and layer < num_layers - 1:
            key, sub = jax.random.split(key)
            mask = jax.random.bernoulli(sub, 1.0 - p, x.shape)
            x = jnp.where(mask, x / (1.0 - p), 0.0).astype(x.dtype)
    output = x
    if not state_outputs:
        return output
    h_out = jnp.stack(h_finals, axis=0)
    if mode == "lstm":
        return output, h_out, jnp.stack(c_finals, axis=0)
    return output, h_out
