"""Reduction / sorting / argmin-max ops.

Reference parity: src/operator/tensor/broadcast_reduce_op_value.cc,
ordering_op.cc (topk/sort/argsort), src/operator/tensor/matrix_op (norm).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register


def _norm_axis(axis):
    if axis is None or axis == ():
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def _reduce(f):
    def impl(data, axis=None, keepdims=False, exclude=False):
        axis = _norm_axis(axis)
        if exclude and axis is not None:
            ax = axis if isinstance(axis, tuple) else (axis,)
            ax = tuple(a % data.ndim for a in ax)
            axis = tuple(i for i in range(data.ndim) if i not in ax)
        return f(data, axis=axis, keepdims=keepdims)

    return impl


register("sum", aliases=("sum_axis",))(_reduce(jnp.sum))
register("mean")(_reduce(jnp.mean))
register("prod")(_reduce(jnp.prod))
register("max", aliases=("max_axis",))(_reduce(jnp.max))
register("min", aliases=("min_axis",))(_reduce(jnp.min))
register("nansum")(_reduce(jnp.nansum))
register("nanprod")(_reduce(jnp.nanprod))


@register("argmax")
def argmax(data, axis=None, keepdims=False):
    out = jnp.argmax(data, axis=axis, keepdims=keepdims)
    return out.astype(jnp.float32)  # reference returns float indices


@register("argmin")
def argmin(data, axis=None, keepdims=False):
    return jnp.argmin(data, axis=axis, keepdims=keepdims).astype(jnp.float32)


@register("argmax_channel")
def argmax_channel(data):
    return jnp.argmax(data, axis=-1).astype(jnp.float32)


@register("norm")
def norm(data, ord=2, axis=None, keepdims=False):
    axis = _norm_axis(axis)
    if ord == 1:
        return jnp.sum(jnp.abs(data), axis=axis, keepdims=keepdims)
    return jnp.sqrt(jnp.sum(jnp.square(data), axis=axis, keepdims=keepdims))


@register("L2Normalization")
def l2_normalization(data, eps=1e-10, mode="instance"):
    if mode == "instance":
        axes = tuple(range(1, data.ndim))
    elif mode == "channel":
        axes = (1,)
    else:  # spatial
        axes = tuple(range(2, data.ndim))
    n = jnp.sqrt(jnp.sum(jnp.square(data), axis=axes, keepdims=True) + eps)
    return data / n


@register("topk")
def topk(data, axis=-1, k=1, ret_typ="indices", is_ascend=False, dtype="float32"):
    x = jnp.moveaxis(data, axis, -1)
    if is_ascend:
        x = -x
    vals, idx = lax.top_k(x, k)
    if is_ascend:
        vals = -vals
    vals = jnp.moveaxis(vals, -1, axis)
    idx = jnp.moveaxis(idx, -1, axis).astype(dtype)
    if ret_typ == "value":
        return vals
    if ret_typ == "both":
        return vals, idx
    if ret_typ == "mask":
        # 1 at the top-k positions, 0 elsewhere (reference: ordering_op
        # ret_typ=mask)
        k_idx = jnp.moveaxis(idx, axis, -1).astype(jnp.int32)
        mask = jnp.sum(jax.nn.one_hot(k_idx, x.shape[-1],
                                      dtype=data.dtype), axis=-2)
        return jnp.moveaxis(mask, -1, axis)
    return idx


@register("sort")
def sort(data, axis=-1, is_ascend=True):
    out = jnp.sort(data, axis=axis)
    if not is_ascend:
        out = jnp.flip(out, axis=axis)
    return out


@register("argsort")
def argsort(data, axis=-1, is_ascend=True, dtype="float32"):
    out = jnp.argsort(data, axis=axis)
    if not is_ascend:
        out = jnp.flip(out, axis=axis)
    return out.astype(dtype)


@register("cumsum")
def cumsum(a, axis=None, dtype=None):
    return jnp.cumsum(a, axis=axis, dtype=dtype)


@register("cumprod")
def cumprod(a, axis=None, dtype=None):
    return jnp.cumprod(a, axis=axis, dtype=dtype)
