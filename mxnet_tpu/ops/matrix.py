"""Matrix / shape-manipulation ops.

Reference parity: src/operator/tensor/matrix_op.cc (reshape/transpose/concat/
slice/tile/pad/...), dot.cc (dot, batch_dot).  ``dot`` lowers to the MXU via
lax.dot_general with a bfloat16-friendly preferred_element_type.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .registry import register


@register("dot")
def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """Reference dot semantics: reduce last axis of lhs with first of rhs
    (after optional transposes) — N-D generalization included."""
    if transpose_a:
        lhs = jnp.moveaxis(lhs, 0, -1) if lhs.ndim > 1 else lhs
    if transpose_b:
        rhs = jnp.moveaxis(rhs, -1, 0) if rhs.ndim > 1 else rhs
    if lhs.ndim == 1 and rhs.ndim == 1:
        return jnp.dot(lhs, rhs)
    return jnp.tensordot(lhs, rhs, axes=([lhs.ndim - 1], [0]))


@register("batch_dot")
def batch_dot(lhs, rhs, transpose_a=False, transpose_b=False):
    if transpose_a:
        lhs = jnp.swapaxes(lhs, -1, -2)
    if transpose_b:
        rhs = jnp.swapaxes(rhs, -1, -2)
    return jnp.matmul(lhs, rhs)


@register("transpose")
def transpose(data, axes=None):
    if axes is None or axes == ():
        axes = tuple(reversed(range(data.ndim)))
    return jnp.transpose(data, axes)


@register("swapaxes", aliases=("SwapAxis",))
def swapaxes(data, dim1=0, dim2=0):
    return jnp.swapaxes(data, dim1, dim2)


@register("reshape", aliases=("Reshape",))
def reshape(data, shape=None, reverse=False):
    """Supports the reference's special codes 0 (keep), -1 (infer),
    -2 (copy rest), -3 (merge two), -4 (split) — src/operator/tensor/
    matrix_op-inl.h ReshapeShape."""
    shape = tuple(int(s) for s in shape)
    if not any(s in (0, -2, -3, -4) for s in shape):
        return jnp.reshape(data, shape)
    src = list(data.shape)
    if reverse:
        src = src[::-1]
        shape = tuple(reversed(shape))
    out: list[int] = []
    i = 0  # cursor into src
    j = 0
    shape_l = list(shape)
    while j < len(shape_l):
        s = shape_l[j]
        if s == 0:
            out.append(src[i]); i += 1
        elif s == -2:
            out.extend(src[i:]); i = len(src)
        elif s == -3:
            out.append(src[i] * src[i + 1]); i += 2
        elif s == -4:
            a, b = shape_l[j + 1], shape_l[j + 2]
            if a == -1:
                a = src[i] // b
            elif b == -1:
                b = src[i] // a
            out.extend([a, b]); i += 1; j += 2
        elif s == -1:
            out.append(-1); i += 1
        else:
            out.append(s); i += 1
        j += 1
    if reverse:
        out = out[::-1]
    return jnp.reshape(data, tuple(out))


@register("reshape_like")
def reshape_like(lhs, rhs):
    if isinstance(rhs, (tuple, list)) or isinstance(lhs, (tuple, list)):
        # the classic foot-gun: a multi-output net's tuple fed to a loss
        raise TypeError(
            "reshape_like: got a tuple/list operand — a multi-output "
            "network's result was passed where one array is expected "
            "(select the output first, e.g. out[0])")
    return jnp.reshape(lhs, rhs.shape)


@register("expand_dims")
def expand_dims(data, axis=0):
    return jnp.expand_dims(data, axis)


@register("squeeze")
def squeeze(data, axis=None):
    return jnp.squeeze(data, axis=axis)


@register("flatten", aliases=("Flatten",))
def flatten(data):
    return jnp.reshape(data, (data.shape[0], -1))


@register("concat", aliases=("Concat",))
def concat(*data, dim=1):
    return jnp.concatenate(data, axis=dim)


@register("stack")
def stack(*data, axis=0):
    return jnp.stack(data, axis=axis)


@register("split", aliases=("SliceChannel",))
def split(data, num_outputs=1, axis=1, squeeze_axis=False):
    parts = jnp.split(data, num_outputs, axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts) if len(parts) > 1 else parts[0]


@register("slice")
def slice(data, begin=None, end=None, step=None):  # noqa: A001
    import builtins

    ndim = data.ndim
    begin = list(begin) + [None] * (ndim - len(begin))
    end = list(end) + [None] * (ndim - len(end))
    step = list(step or []) + [None] * (ndim - len(step or []))
    idx = tuple(builtins.slice(b, e, s)
                for b, e, s in zip(begin, end, step))
    return data[idx]


@register("slice_axis")
def slice_axis(data, axis=0, begin=0, end=None):
    import builtins

    idx = [builtins.slice(None)] * data.ndim
    idx[axis] = builtins.slice(begin, end)
    return data[tuple(idx)]


@register("slice_like")
def slice_like(data, shape_like, axes=()):
    import builtins

    idx = [builtins.slice(None)] * data.ndim
    axes = axes or range(min(data.ndim, shape_like.ndim))
    for a in axes:
        idx[a] = builtins.slice(0, shape_like.shape[a])
    return data[tuple(idx)]


@register("tile")
def tile(data, reps=()):
    return jnp.tile(data, reps)


@register("repeat")
def repeat(data, repeats=1, axis=None):
    return jnp.repeat(data, repeats, axis=axis)


@register("pad", aliases=("Pad",))
def pad(data, mode="constant", pad_width=(), constant_value=0.0):
    pw = [(pad_width[2 * i], pad_width[2 * i + 1]) for i in range(len(pad_width) // 2)]
    jmode = {"constant": "constant", "edge": "edge", "reflect": "reflect"}[mode]
    kw = {"constant_values": constant_value} if mode == "constant" else {}
    return jnp.pad(data, pw, mode=jmode, **kw)


@register("flip", aliases=("reverse",))
def flip(data, axis=()):
    return jnp.flip(data, axis=axis)


@register("broadcast_to")
def broadcast_to(data, shape=None):
    shape = tuple(d if s == 0 else s for s, d in zip(shape, data.shape))
    return jnp.broadcast_to(data, shape)


@register("broadcast_like")
def broadcast_like(lhs, rhs):
    return jnp.broadcast_to(lhs, rhs.shape)


@register("broadcast_axis", aliases=("broadcast_axes",))
def broadcast_axis(data, axis=(), size=()):
    axis = axis if isinstance(axis, (list, tuple)) else (axis,)
    size = size if isinstance(size, (list, tuple)) else (size,)
    shape = list(data.shape)
    for a, s in zip(axis, size):
        shape[a] = s
    return jnp.broadcast_to(data, tuple(shape))


@register("zeros_like")
def zeros_like(data):
    return jnp.zeros_like(data)


@register("ones_like")
def ones_like(data):
    return jnp.ones_like(data)


@register("full_like")
def full_like(data, fill_value=0.0):
    return jnp.full_like(data, fill_value)


@register("where")
def where(condition, x, y):
    return jnp.where(condition.astype(bool), x, y)


@register("cast", aliases=("Cast",))
def cast(data, dtype="float32"):
    from ..base import np_dtype, x64_scope_if

    with x64_scope_if(dtype):
        return data.astype(np_dtype(dtype))


@register("amp_cast")
def amp_cast(data, dtype="float16"):
    from ..base import np_dtype

    return data.astype(np_dtype(dtype))


@register("shape_array")
def shape_array(data):
    return jnp.asarray(data.shape, dtype=jnp.int64 if False else jnp.int32)


@register("size_array")
def size_array(data):
    return jnp.asarray([data.size], dtype=jnp.int32)


@register("diag")
def diag(data, k=0):
    return jnp.diag(data, k=k) if data.ndim <= 2 else jnp.diagonal(
        data, offset=k, axis1=-2, axis2=-1)


@register("identity", aliases=("_copy", "copy"))
def identity(data):
    return data  # immutable arrays: copy is free


@register("stop_gradient", aliases=("BlockGrad", "block_grad"))
def stop_gradient(data):
    return lax.stop_gradient(data)


@register("depth_to_space")
def depth_to_space(data, block_size=2):
    b = block_size
    n, c, h, w = data.shape
    x = data.reshape(n, b, b, c // (b * b), h, w)
    x = x.transpose(0, 3, 4, 1, 5, 2)
    return x.reshape(n, c // (b * b), h * b, w * b)


@register("space_to_depth")
def space_to_depth(data, block_size=2):
    b = block_size
    n, c, h, w = data.shape
    x = data.reshape(n, c, h // b, b, w // b, b)
    x = x.transpose(0, 3, 5, 1, 2, 4)
    return x.reshape(n, c * b * b, h // b, w // b)


@register("_sym_index")
def _sym_index(data, index_spec=None):
    """Decode the JSON index spec Symbol.__getitem__ encodes (symbolic
    array indexing: pos_table[:T], seq[:, 0, :], ...)."""
    import builtins  # the registered `slice` op shadows the builtin

    idx = []
    for item in index_spec or []:
        tag = item[0]
        if tag == "i":
            idx.append(int(item[1]))
        elif tag == "s":
            idx.append(builtins.slice(item[1], item[2], item[3]))
        elif tag == "e":
            idx.append(Ellipsis)
        else:
            idx.append(None)
    return data[tuple(idx)]
