"""Linear-algebra ops.

Reference parity: src/operator/tensor/la_op.cc (linalg_gemm, potrf, trsm,
syrk, gelqf, syevd, ...) — mapped onto jax.numpy.linalg / lax.linalg, which
lower to XLA's TPU-supported decompositions.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .registry import register


@register("linalg_gemm")
def linalg_gemm(A, B, C, transpose_a=False, transpose_b=False, alpha=1.0,
                beta=1.0, axis=-2):
    a = jnp.swapaxes(A, -1, -2) if transpose_a else A
    b = jnp.swapaxes(B, -1, -2) if transpose_b else B
    return alpha * jnp.matmul(a, b) + beta * C


@register("linalg_gemm2")
def linalg_gemm2(A, B, transpose_a=False, transpose_b=False, alpha=1.0,
                 axis=-2):
    a = jnp.swapaxes(A, -1, -2) if transpose_a else A
    b = jnp.swapaxes(B, -1, -2) if transpose_b else B
    return alpha * jnp.matmul(a, b)


@register("linalg_potrf")
def linalg_potrf(A):
    return jnp.linalg.cholesky(A)


@register("linalg_potri")
def linalg_potri(A):
    # inverse from cholesky factor: inv(L L^T)
    L = A
    eye = jnp.broadcast_to(jnp.eye(L.shape[-1], dtype=L.dtype), L.shape)
    Linv = lax.linalg.triangular_solve(L, eye, lower=True, left_side=True)
    return jnp.matmul(jnp.swapaxes(Linv, -1, -2), Linv)


@register("linalg_trsm")
def linalg_trsm(A, B, transpose=False, rightside=False, lower=True,
                alpha=1.0):
    out = lax.linalg.triangular_solve(
        A, alpha * B, left_side=not rightside, lower=lower,
        transpose_a=transpose)
    return out


@register("linalg_trmm")
def linalg_trmm(A, B, transpose=False, rightside=False, lower=True,
                alpha=1.0):
    tri = jnp.tril(A) if lower else jnp.triu(A)
    if transpose:
        tri = jnp.swapaxes(tri, -1, -2)
    return alpha * (jnp.matmul(B, tri) if rightside else jnp.matmul(tri, B))


@register("linalg_syrk")
def linalg_syrk(A, transpose=False, alpha=1.0):
    at = jnp.swapaxes(A, -1, -2)
    return alpha * (jnp.matmul(at, A) if transpose else jnp.matmul(A, at))


@register("linalg_gelqf")
def linalg_gelqf(A):
    q, r = jnp.linalg.qr(jnp.swapaxes(A, -1, -2))
    return jnp.swapaxes(r, -1, -2), jnp.swapaxes(q, -1, -2)


@register("linalg_syevd")
def linalg_syevd(A):
    w, v = jnp.linalg.eigh(A)
    return jnp.swapaxes(v, -1, -2), w


@register("linalg_sumlogdiag")
def linalg_sumlogdiag(A):
    d = jnp.diagonal(A, axis1=-2, axis2=-1)
    return jnp.sum(jnp.log(d), axis=-1)


@register("linalg_extractdiag")
def linalg_extractdiag(A, offset=0):
    return jnp.diagonal(A, offset=offset, axis1=-2, axis2=-1)


@register("linalg_makediag")
def linalg_makediag(A, offset=0):
    n = A.shape[-1] + abs(offset)
    out = jnp.zeros(A.shape[:-1] + (n, n), dtype=A.dtype)
    idx = jnp.arange(A.shape[-1])
    if offset >= 0:
        return out.at[..., idx, idx + offset].set(A)
    return out.at[..., idx - offset, idx].set(A)


@register("linalg_inverse", aliases=("inverse",))
def linalg_inverse(A):
    return jnp.linalg.inv(A)


@register("linalg_det", aliases=("det",))
def linalg_det(A):
    return jnp.linalg.det(A)


@register("linalg_slogdet", aliases=("slogdet",))
def linalg_slogdet(A):
    sign, logdet = jnp.linalg.slogdet(A)
    return sign, logdet


@register("linalg_svd", aliases=("svd",))
def linalg_svd(A):
    u, s, vt = jnp.linalg.svd(A, full_matrices=False)
    return u, s, vt


@register("linalg_maketrian")
def linalg_maketrian(A, offset=0, lower=True):
    # invert extracttrian: packed vector -> triangular matrix with the same
    # (offset, lower) convention (reference: src/operator/tensor/la_op.cc)
    import numpy as _host_np

    m = A.shape[-1]
    k = int(offset)
    n = 1
    while len((_host_np.tril_indices(n, k) if lower
               else _host_np.triu_indices(n, k))[0]) < m:
        n += 1
    out = jnp.zeros(A.shape[:-1] + (n, n), dtype=A.dtype)
    rows, cols = (jnp.tril_indices(n, k) if lower
                  else jnp.triu_indices(n, k))
    return out.at[..., rows, cols].set(A)


@register("linalg_extracttrian")
def linalg_extracttrian(A, offset=0, lower=True):
    n = A.shape[-1]
    k = int(offset)
    rows, cols = (jnp.tril_indices(n, k) if lower
                  else jnp.triu_indices(n, k))
    return A[..., rows, cols]
