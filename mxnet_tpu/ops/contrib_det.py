"""Detection ops (SSD / Faster-RCNN family).

Reference parity: src/operator/contrib/ — MultiBoxPrior, MultiBoxTarget,
MultiBoxDetection (multibox_*.cc), box_nms/box_iou/bipartite_matching
(bounding_box.cc), ROIPooling (../roi_pooling.cc), ROIAlign
(roi_align.cc).

TPU-first: these were the reference's dynamic-shape CUDA kernels; here they
are STATIC-shape jax programs (SURVEY.md §7 hard-parts item): NMS keeps the
fixed-length score-sorted list and marks suppressed entries invalid (-1)
instead of shrinking, exactly the padded contract the reference's
``box_nms`` already exposes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register


def _corner_iou(a, b):
    """IoU of (..., 4) corner boxes against (..., 4)."""
    tl = jnp.maximum(a[..., :2], b[..., :2])
    br = jnp.minimum(a[..., 2:4], b[..., 2:4])
    wh = jnp.maximum(br - tl, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = jnp.maximum(a[..., 2] - a[..., 0], 0.0) * \
        jnp.maximum(a[..., 3] - a[..., 1], 0.0)
    area_b = jnp.maximum(b[..., 2] - b[..., 0], 0.0) * \
        jnp.maximum(b[..., 3] - b[..., 1], 0.0)
    return inter / jnp.maximum(area_a + area_b - inter, 1e-12)


@register("_contrib_box_iou", aliases=("box_iou",))
def box_iou(lhs, rhs, format="corner"):
    """Pairwise IoU (reference: bounding_box.cc box_iou)."""
    if format == "center":
        lhs = _center_to_corner(lhs)
        rhs = _center_to_corner(rhs)
    return _corner_iou(lhs[..., :, None, :], rhs[..., None, :, :])


def _center_to_corner(b):
    x, y, w, h = (b[..., 0], b[..., 1], b[..., 2], b[..., 3])
    return jnp.stack([x - w / 2, y - h / 2, x + w / 2, y + h / 2],
                     axis=-1)


@register("_contrib_box_nms", aliases=("box_nms",))
def box_nms(data, overlap_thresh=0.5, valid_thresh=0.0, topk=-1,
            coord_start=2, score_index=1, id_index=-1, background_id=-1,
            force_suppress=False, in_format="corner",
            out_format="corner"):
    """Greedy NMS with the reference's padded semantics: output has the
    SAME shape, suppressed/invalid entries have score (and id) set to -1.

    data: (..., N, K) with scores at score_index and box corners at
    coord_start..coord_start+4.
    """
    batched = data.ndim == 3
    if not batched:
        data = data[None]

    def one(sample):
        N = sample.shape[0]
        scores = sample[:, score_index]
        boxes = lax.dynamic_slice_in_dim(sample, coord_start, 4, axis=1)
        if in_format == "center":
            boxes = _center_to_corner(boxes)
        ids = sample[:, id_index] if id_index >= 0 else \
            jnp.zeros((N,))
        valid = scores > valid_thresh
        if id_index >= 0 and background_id >= 0:
            valid &= ids != background_id
        order = jnp.argsort(-jnp.where(valid, scores, -jnp.inf))
        k = N if topk <= 0 else min(topk, N)
        sboxes = boxes[order]
        svalid = valid[order]
        sids = ids[order]
        # boxes ranked past topk are dropped outright
        rank = jnp.arange(N)
        svalid &= rank < k
        iou = _corner_iou(sboxes[:, None, :], sboxes[None, :, :])
        same_class = jnp.ones((N, N), bool) if force_suppress or \
            id_index < 0 else (sids[:, None] == sids[None, :])
        suppress_pair = (iou > overlap_thresh) & same_class

        def body(i, keep):
            # i suppresses later j when i itself is kept
            cur = keep[i] & svalid[i]
            mask = suppress_pair[i] & (jnp.arange(N) > i) & cur
            return keep & ~mask

        keep = lax.fori_loop(0, N, body, jnp.ones((N,), bool))
        keep &= svalid
        out = sample[order]
        out = out.at[:, score_index].set(
            jnp.where(keep, out[:, score_index], -1.0))
        if id_index >= 0:
            out = out.at[:, id_index].set(
                jnp.where(keep, out[:, id_index], -1.0))
        return out

    out = jax.vmap(one)(data)
    return out if batched else out[0]


@register("_contrib_bipartite_matching", aliases=("bipartite_matching",))
def bipartite_matching(data, threshold=0.5, is_ascend=False, topk=-1):
    """Greedy bipartite matching (reference: bounding_box.cc).

    data: (B, N, M) pairwise scores → (row_match (B,N), col_match (B,M)).
    """
    def one(scores):
        N, M = scores.shape
        order = -scores if not is_ascend else scores
        row = jnp.full((N,), -1.0)
        col = jnp.full((M,), -1.0)
        k = min(N, M) if topk <= 0 else min(topk, min(N, M))

        def body(_, state):
            row, col, s = state
            idx = jnp.argmin(s) if is_ascend else jnp.argmax(s)
            i, j = idx // M, idx % M
            val = s[i, j]
            ok = (val >= threshold) if not is_ascend else \
                (val <= threshold)
            ok &= (row[i] < 0) & (col[j] < 0)
            row = jnp.where(ok, row.at[i].set(j.astype(row.dtype)), row)
            col = jnp.where(ok, col.at[j].set(i.astype(col.dtype)), col)
            blocked = s.at[i, :].set(-jnp.inf if not is_ascend
                                     else jnp.inf)
            blocked = blocked.at[:, j].set(-jnp.inf if not is_ascend
                                           else jnp.inf)
            s = jnp.where(ok, blocked, blocked)  # always block the pair
            return row, col, s

        row, col, _ = lax.fori_loop(0, k, body,
                                    (row, col, scores.astype(jnp.float32)))
        return row, col

    return jax.vmap(one)(data)


@register("MultiBoxPrior", aliases=("multibox_prior",))
def multibox_prior(data, sizes=(1.0,), ratios=(1.0,), clip=False,
                   steps=(-1.0, -1.0), offsets=(0.5, 0.5)):
    """Anchor generation (reference: multibox_prior.cc).  data gives the
    feature map (B, C, H, W); output (1, H*W*(S+R-1), 4) corner anchors."""
    H, W = data.shape[2], data.shape[3]
    sizes = tuple(sizes)
    ratios = tuple(ratios)
    step_y = steps[0] if steps[0] > 0 else 1.0 / H
    step_x = steps[1] if steps[1] > 0 else 1.0 / W
    cy = (jnp.arange(H) + offsets[0]) * step_y
    cx = (jnp.arange(W) + offsets[1]) * step_x
    cyx = jnp.stack(jnp.meshgrid(cy, cx, indexing="ij"),
                    axis=-1).reshape(H * W, 2)
    wh = []
    for i, s in enumerate(sizes):
        wh.append((s * jnp.sqrt(ratios[0]), s / jnp.sqrt(ratios[0])))
    for r in ratios[1:]:
        wh.append((sizes[0] * jnp.sqrt(r), sizes[0] / jnp.sqrt(r)))
    wh = jnp.asarray(wh)  # (A, 2) as (w, h)
    A = wh.shape[0]
    centers = jnp.repeat(cyx, A, axis=0)          # (HW*A, 2) (cy, cx)
    whs = jnp.tile(wh, (H * W, 1))                # (HW*A, 2)
    anchors = jnp.stack([
        centers[:, 1] - whs[:, 0] / 2,   # xmin
        centers[:, 0] - whs[:, 1] / 2,   # ymin
        centers[:, 1] + whs[:, 0] / 2,   # xmax
        centers[:, 0] + whs[:, 1] / 2,   # ymax
    ], axis=-1)
    if clip:
        anchors = jnp.clip(anchors, 0.0, 1.0)
    return anchors[None]


@register("MultiBoxTarget", aliases=("multibox_target",))
def multibox_target(anchor, label, cls_pred, overlap_threshold=0.5,
                    ignore_label=-1, negative_mining_ratio=-1,
                    negative_mining_thresh=0.5, minimum_negative_samples=0,
                    variances=(0.1, 0.1, 0.2, 0.2)):
    """Match anchors to ground truth and encode regression targets
    (reference: multibox_target.cc).

    anchor: (1, N, 4) corners; label: (B, M, 5) [cls, xmin, ymin, xmax,
    ymax] padded with cls=-1; returns (loc_target (B, N*4),
    loc_mask (B, N*4), cls_target (B, N))."""
    anchors = anchor[0]  # (N, 4)
    N = anchors.shape[0]
    var = jnp.asarray(variances)

    def one(lab, pred):
        gt_valid = lab[:, 0] >= 0
        gt_boxes = lab[:, 1:5]
        iou = _corner_iou(anchors[:, None, :], gt_boxes[None, :, :])
        iou = jnp.where(gt_valid[None, :], iou, -1.0)
        best_gt = jnp.argmax(iou, axis=1)          # per anchor
        best_iou = jnp.max(iou, axis=1)
        matched = best_iou >= overlap_threshold
        # force-match: each valid gt claims its best anchor
        best_anchor = jnp.argmax(iou, axis=0)       # per gt
        # .max, not .set: padded gts share argmax index 0 with real gts
        # and a duplicate-index .set would let their False win
        force = jnp.zeros((N,), bool)
        force = force.at[best_anchor].max(gt_valid)
        gt_of_anchor = jnp.where(
            force, jnp.argmax(
                jnp.where(force[:, None],
                          (best_anchor[None, :] ==
                           jnp.arange(N)[:, None]) * 1.0, 0.0), axis=1),
            best_gt)
        matched = matched | force
        g = gt_boxes[gt_of_anchor]
        # encode center offsets normalized by variances
        aw = anchors[:, 2] - anchors[:, 0]
        ah = anchors[:, 3] - anchors[:, 1]
        acx = (anchors[:, 0] + anchors[:, 2]) / 2
        acy = (anchors[:, 1] + anchors[:, 3]) / 2
        gw = jnp.maximum(g[:, 2] - g[:, 0], 1e-8)
        gh = jnp.maximum(g[:, 3] - g[:, 1], 1e-8)
        gcx = (g[:, 0] + g[:, 2]) / 2
        gcy = (g[:, 1] + g[:, 3]) / 2
        loc = jnp.stack([
            (gcx - acx) / jnp.maximum(aw, 1e-8) / var[0],
            (gcy - acy) / jnp.maximum(ah, 1e-8) / var[1],
            jnp.log(gw / jnp.maximum(aw, 1e-8)) / var[2],
            jnp.log(gh / jnp.maximum(ah, 1e-8)) / var[3]], axis=-1)
        loc_mask = jnp.repeat(matched.astype(jnp.float32), 4)
        loc_target = (loc * matched[:, None]).reshape(-1)
        cls_target = jnp.where(matched,
                               lab[gt_of_anchor, 0] + 1.0, 0.0)
        if negative_mining_ratio > 0:
            # hard negative mining (reference: multibox_target.cc): keep
            # only the most-confidently-wrong negatives; the rest get
            # ignore_label and drop out of the classification loss
            max_fg = jnp.max(pred[1:], axis=0) if pred.shape[0] > 1 \
                else pred[0]
            neg_cand = (~matched) & (best_iou < negative_mining_thresh)
            num_neg = jnp.maximum(
                jnp.sum(matched) * negative_mining_ratio,
                minimum_negative_samples)
            negness = jnp.where(neg_cand, max_fg, -jnp.inf)
            rank = jnp.argsort(jnp.argsort(-negness))
            selected_neg = neg_cand & (rank < num_neg)
            cls_target = jnp.where(
                matched, cls_target,
                jnp.where(selected_neg, 0.0, float(ignore_label)))
        return loc_target, loc_mask, cls_target

    loc_t, loc_m, cls_t = jax.vmap(one)(label, cls_pred)
    return loc_t, loc_m, cls_t


@register("MultiBoxDetection", aliases=("multibox_detection",))
def multibox_detection(cls_prob, loc_pred, anchor, clip=True,
                       threshold=0.01, background_id=0, nms_threshold=0.5,
                       force_suppress=False,
                       variances=(0.1, 0.1, 0.2, 0.2), nms_topk=-1):
    """Decode predictions into detections + NMS (reference:
    multibox_detection.cc).  Output (B, N, 6): [id, score, xmin, ymin,
    xmax, ymax], invalid rows id=-1."""
    anchors = anchor[0]
    var = jnp.asarray(variances)
    B, C, N = cls_prob.shape

    def one(prob, loc):
        loc = loc.reshape(N, 4)
        aw = anchors[:, 2] - anchors[:, 0]
        ah = anchors[:, 3] - anchors[:, 1]
        acx = (anchors[:, 0] + anchors[:, 2]) / 2
        acy = (anchors[:, 1] + anchors[:, 3]) / 2
        cx = loc[:, 0] * var[0] * aw + acx
        cy = loc[:, 1] * var[1] * ah + acy
        w = jnp.exp(loc[:, 2] * var[2]) * aw
        h = jnp.exp(loc[:, 3] * var[3]) * ah
        boxes = jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2,
                           cy + h / 2], axis=-1)
        if clip:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        # best non-background class per anchor
        fg = jnp.concatenate(
            [prob[:background_id], prob[background_id + 1:]], axis=0) \
            if C > 1 else prob
        cls_id = jnp.argmax(fg, axis=0).astype(jnp.float32)
        score = jnp.max(fg, axis=0)
        keep = score > threshold
        det = jnp.concatenate([
            jnp.where(keep, cls_id, -1.0)[:, None],
            jnp.where(keep, score, -1.0)[:, None], boxes], axis=-1)
        det = box_nms(det, overlap_thresh=nms_threshold, valid_thresh=0.0,
                      topk=nms_topk, coord_start=2, score_index=1,
                      id_index=0, force_suppress=force_suppress)
        return det

    return jax.vmap(one)(cls_prob, loc_pred)


@register("ROIPooling", aliases=("roi_pooling",))
def roi_pooling(data, rois, pooled_size=(7, 7), spatial_scale=1.0):
    """Max ROI pooling (reference: src/operator/roi_pooling.cc).
    data (B,C,H,W); rois (R,5) [batch_idx, x1, y1, x2, y2]."""
    PH, PW = pooled_size
    B, C, H, W = data.shape

    def one(roi):
        b = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1] * spatial_scale).astype(jnp.int32)
        y1 = jnp.round(roi[2] * spatial_scale).astype(jnp.int32)
        x2 = jnp.round(roi[3] * spatial_scale).astype(jnp.int32)
        y2 = jnp.round(roi[4] * spatial_scale).astype(jnp.int32)
        rw = jnp.maximum(x2 - x1 + 1, 1)
        rh = jnp.maximum(y2 - y1 + 1, 1)
        img = data[b]  # (C,H,W)
        ys = jnp.arange(H)
        xs = jnp.arange(W)

        def cell(py, px):
            hs = y1 + (py * rh) // PH
            he = y1 + -(-((py + 1) * rh) // PH)
            ws = x1 + (px * rw) // PW
            we = x1 + -(-((px + 1) * rw) // PW)
            mask = ((ys[:, None] >= hs) & (ys[:, None] < he)
                    & (xs[None, :] >= ws) & (xs[None, :] < we))
            vals = jnp.where(mask[None], img, -jnp.inf)
            out = jnp.max(vals, axis=(1, 2))
            return jnp.where(jnp.isfinite(out), out, 0.0)

        py, px = jnp.meshgrid(jnp.arange(PH), jnp.arange(PW),
                              indexing="ij")
        cells = jax.vmap(jax.vmap(cell))(py, px)  # (PH, PW, C)
        return jnp.transpose(cells, (2, 0, 1))

    return jax.vmap(one)(rois)


@register("_contrib_ROIAlign", aliases=("roi_align", "ROIAlign"))
def roi_align(data, rois, pooled_size=(7, 7), spatial_scale=1.0,
              sample_ratio=2, position_sensitive=False, aligned=False):
    """Bilinear ROI align (reference: roi_align.cc, Mask-RCNN)."""
    PH, PW = pooled_size
    B, C, H, W = data.shape
    offset = 0.5 if aligned else 0.0
    sr = max(int(sample_ratio), 1)

    def bilinear(img, y, x):
        y = jnp.clip(y, 0.0, H - 1.0)
        x = jnp.clip(x, 0.0, W - 1.0)
        y0 = jnp.floor(y).astype(jnp.int32)
        x0 = jnp.floor(x).astype(jnp.int32)
        y1 = jnp.minimum(y0 + 1, H - 1)
        x1 = jnp.minimum(x0 + 1, W - 1)
        wy1 = y - y0
        wx1 = x - x0
        v = (img[:, y0, x0] * (1 - wy1) * (1 - wx1)
             + img[:, y1, x0] * wy1 * (1 - wx1)
             + img[:, y0, x1] * (1 - wy1) * wx1
             + img[:, y1, x1] * wy1 * wx1)
        return v

    def one(roi):
        b = roi[0].astype(jnp.int32)
        x1 = roi[1] * spatial_scale - offset
        y1 = roi[2] * spatial_scale - offset
        x2 = roi[3] * spatial_scale - offset
        y2 = roi[4] * spatial_scale - offset
        rw = jnp.maximum(x2 - x1, 1.0 if not aligned else 1e-8)
        rh = jnp.maximum(y2 - y1, 1.0 if not aligned else 1e-8)
        bin_w = rw / PW
        bin_h = rh / PH
        img = data[b]

        def cell(py, px):
            acc = jnp.zeros((C,))
            for iy in range(sr):
                for ix in range(sr):
                    y = y1 + (py + (iy + 0.5) / sr) * bin_h
                    x = x1 + (px + (ix + 0.5) / sr) * bin_w
                    acc = acc + bilinear(img, y, x)
            return acc / (sr * sr)

        py, px = jnp.meshgrid(jnp.arange(PH, dtype=jnp.float32),
                              jnp.arange(PW, dtype=jnp.float32),
                              indexing="ij")
        cells = jax.vmap(jax.vmap(cell))(py, px)
        return jnp.transpose(cells, (2, 0, 1))

    return jax.vmap(one)(rois)
