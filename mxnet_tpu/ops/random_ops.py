"""Random sampling ops.

Reference parity: src/operator/random/sample_op.cc (uniform/normal/gamma/
exponential/poisson/negative_binomial samplers), multisample_op.cc,
shuffle_op.cc.  All keyed on the functional PRNG (see mxnet_tpu.random).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register
from ..base import np_dtype


def _shape(shape):
    if shape is None:
        return ()
    if isinstance(shape, int):
        return (shape,)
    return tuple(int(s) for s in shape)


@register("random_uniform", aliases=("uniform",), random=True)
def random_uniform(low=0.0, high=1.0, shape=None, dtype="float32", _key=None):
    return jax.random.uniform(_key, _shape(shape), np_dtype(dtype),
                              minval=low, maxval=high)


@register("random_normal", aliases=("normal",), random=True)
def random_normal(loc=0.0, scale=1.0, shape=None, dtype="float32", _key=None):
    return loc + scale * jax.random.normal(_key, _shape(shape),
                                           np_dtype(dtype))


@register("random_gamma", aliases=("gamma_sample",), random=True)
def random_gamma(alpha=1.0, beta=1.0, shape=None, dtype="float32", _key=None):
    return beta * jax.random.gamma(_key, alpha, _shape(shape),
                                   np_dtype(dtype))


@register("random_exponential", aliases=("exponential",), random=True)
def random_exponential(lam=1.0, shape=None, dtype="float32", _key=None):
    return jax.random.exponential(_key, _shape(shape), np_dtype(dtype)) / lam


@register("random_poisson", aliases=("poisson",), random=True)
def random_poisson(lam=1.0, shape=None, dtype="float32", _key=None):
    return jax.random.poisson(_key, lam, _shape(shape)).astype(
        np_dtype(dtype))


@register("random_negative_binomial", aliases=("negative_binomial",),
          random=True)
def random_negative_binomial(k=1, p=1.0, shape=None, dtype="float32",
                             _key=None):
    k1, k2 = jax.random.split(_key)
    lam = jax.random.gamma(k1, k, _shape(shape)) * (1 - p) / p
    return jax.random.poisson(k2, lam, _shape(shape)).astype(np_dtype(dtype))


@register("random_generalized_negative_binomial",
          aliases=("generalized_negative_binomial",), random=True)
def random_gnb(mu=1.0, alpha=1.0, shape=None, dtype="float32", _key=None):
    k1, k2 = jax.random.split(_key)
    r = 1.0 / alpha
    p = r / (r + mu)
    lam = jax.random.gamma(k1, r, _shape(shape)) * (1 - p) / p
    return jax.random.poisson(k2, lam, _shape(shape)).astype(np_dtype(dtype))


@register("random_randint", aliases=("randint",), random=True)
def random_randint(low=0, high=1, shape=None, dtype="int32", _key=None):
    return jax.random.randint(_key, _shape(shape), low, high,
                              np_dtype(dtype))


@register("sample_multinomial", aliases=("multinomial",), random=True)
def sample_multinomial(data, shape=None, get_prob=False, dtype="int32",
                       _key=None):
    n = _shape(shape)
    num = 1
    for s in n:
        num *= s
    logits = jnp.log(jnp.maximum(data, 1e-30))
    if data.ndim == 1:
        out = jax.random.categorical(_key, logits, shape=(num,) if n else ())
        out = out.reshape(n) if n else out
    else:
        out = jax.random.categorical(_key, logits[:, None, :],
                                     axis=-1, shape=(data.shape[0], num))
        out = out.reshape((data.shape[0],) + n) if n else out[:, 0]
    out = out.astype(np_dtype(dtype))
    if get_prob:
        lp = jnp.take_along_axis(
            jax.nn.log_softmax(logits, axis=-1).reshape(-1, logits.shape[-1]),
            out.reshape(data.shape[0] if data.ndim > 1 else 1, -1).astype(
                jnp.int32),
            axis=-1).reshape(out.shape)
        return out, lp
    return out


@register("sample_uniform", random=True)
def sample_uniform(low, high, shape=None, dtype="float32", _key=None):
    s = _shape(shape)
    u = jax.random.uniform(_key, low.shape + s, np_dtype(dtype))
    low_b = low.reshape(low.shape + (1,) * len(s))
    high_b = high.reshape(high.shape + (1,) * len(s))
    return low_b + u * (high_b - low_b)


@register("sample_normal", random=True)
def sample_normal(mu, sigma, shape=None, dtype="float32", _key=None):
    s = _shape(shape)
    z = jax.random.normal(_key, mu.shape + s, np_dtype(dtype))
    return mu.reshape(mu.shape + (1,) * len(s)) + \
        sigma.reshape(sigma.shape + (1,) * len(s)) * z


@register("shuffle", aliases=("random_shuffle",), random=True)
def shuffle(data, _key=None):
    return jax.random.permutation(_key, data, axis=0)


@register("random_bernoulli", aliases=("bernoulli",), random=True)
def random_bernoulli(p=0.5, shape=None, dtype="float32", _key=None):
    return jax.random.bernoulli(_key, p, _shape(shape)).astype(
        np_dtype(dtype))
