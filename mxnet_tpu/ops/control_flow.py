"""Control-flow ops: foreach, while_loop, cond.

Reference parity: src/operator/control_flow.cc (higher-order ops with
subgraphs, landed in MXNet 1.3; Python frontend
python/mxnet/ndarray/contrib.py).  TPU-first: these map 1:1 onto
lax.scan / lax.while_loop / lax.cond, which is exactly the compiler-friendly
control flow XLA wants — no graph-cutting or subgraph ops needed.

The functions here accept either NDArrays or jax arrays (they run the body
through the polymorphic frontend), so they work eagerly and under jit.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def _unwrap(x):
    from ..ndarray import NDArray

    if isinstance(x, NDArray):
        return x._data
    if isinstance(x, (list, tuple)):
        return type(x)(_unwrap(v) for v in x)
    return x


def _wrap_like(x, template):
    from ..ndarray import NDArray, _from_jax

    if isinstance(template, NDArray) or (
            isinstance(template, (list, tuple)) and any(
                isinstance(t, NDArray) for t in template)):
        if isinstance(x, (list, tuple)):
            return type(x)(_from_jax(v) for v in x)
        return _from_jax(x)
    return x


def foreach(body, data, init_states):
    """scan `body` over the leading axis of `data`.

    body(step_data, states) -> (outputs, new_states)
    Returns (stacked_outputs, final_states).
    """
    jdata = _unwrap(data)
    jstates = _unwrap(init_states)

    def scan_body(carry, x):
        out, new_states = body(x, carry)
        return new_states, out

    final, outs = lax.scan(scan_body, jstates, jdata)
    return _wrap_like(outs, data), _wrap_like(final, init_states)


def while_loop(cond, func, loop_vars, max_iterations=None):
    """Reference semantics: run func while cond holds, up to max_iterations.

    func(*loop_vars) -> (step_output, new_loop_vars).  Outputs are stacked
    into a max_iterations-sized buffer (XLA needs static shapes); entries
    beyond the actual iteration count are zeros, and the true count is
    recoverable from the returned loop vars.
    """
    jvars = _unwrap(loop_vars)
    if max_iterations is None:
        # no outputs requested: plain while loop
        def body(vs):
            _, new_vs = func(*vs)
            return tuple(_unwrap(new_vs))

        out_vars = lax.while_loop(
            lambda vs: jnp.asarray(_unwrap(cond(*vs))).reshape(()), body,
            tuple(jvars))
        return [], _wrap_like(list(out_vars), loop_vars)

    # probe one step to learn output structure
    probe_out, _ = func(*loop_vars)
    probe_out = _unwrap(probe_out)
    single = not isinstance(probe_out, (list, tuple))
    probe_list = [probe_out] if single else list(probe_out)
    bufs = [jnp.zeros((max_iterations,) + tuple(p.shape), p.dtype)
            for p in probe_list]

    def body(carry):
        i, vs, bufs = carry
        out, new_vs = func(*vs)
        out = _unwrap(out)
        out_list = [out] if single else list(out)
        bufs = tuple(b.at[i].set(o) for b, o in zip(bufs, out_list))
        return i + 1, tuple(_unwrap(new_vs)), bufs

    def cond_fn(carry):
        i, vs, _ = carry
        return jnp.logical_and(
            i < max_iterations,
            jnp.asarray(_unwrap(cond(*vs))).reshape(()).astype(bool))

    i, out_vars, bufs = lax.while_loop(
        cond_fn, body, (jnp.asarray(0), tuple(jvars), tuple(bufs)))
    outs = [_wrap_like(b, loop_vars[0]) for b in bufs]
    return (outs[0] if single else outs), _wrap_like(
        list(out_vars), loop_vars)


def cond(pred, then_func, else_func, inputs=None):
    """lax.cond with the reference's thunk signature (contrib.cond)."""
    p = jnp.asarray(_unwrap(pred)).reshape(()).astype(bool)
    if inputs is None:
        out = lax.cond(p, lambda _: _unwrap(then_func()),
                       lambda _: _unwrap(else_func()), operand=0)
        return _wrap_like(out, pred)
    jin = tuple(_unwrap(inputs))
    out = lax.cond(p, lambda xs: _unwrap(then_func(*xs)),
                   lambda xs: _unwrap(else_func(*xs)), jin)
    return _wrap_like(out, pred)
