"""Neural-network ops.

Reference parity: src/operator/nn/ (FullyConnected, Convolution, Pooling,
BatchNorm, LayerNorm, Dropout, Activation, softmax family) — reimplemented on
XLA primitives.  Convolutions keep the reference's NCHW/OIHW layout at the API
surface; XLA relayouts for the MXU internally.  Train-mode statefulness
(BatchNorm moving stats, Dropout masks) is functional here: stateful update
lives in the gluon layers.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register


def _pair(v, n=2):
    if isinstance(v, (tuple, list)):
        return tuple(int(x) for x in v)
    return (int(v),) * n


def _conv_f32_accum(data, weight, **cfg):
    """conv with f32 MXU accumulation in the forward pass.

    jax's conv transpose rule rejects ``preferred_element_type=f32`` with
    low-precision operands (the f32 cotangent meets the bf16 kernel), so
    for bf16/fp16 we wrap in a custom_vjp: forward accumulates f32 on the
    MXU, backward runs dgrad/wgrad as native-dtype convs (cuDNN
    tensor-core parity — the TPU MXU still accumulates f32 internally).
    """
    if data.dtype == weight.dtype:
        if data.dtype == jnp.float32:
            return lax.conv_general_dilated(
                data, weight, preferred_element_type=jnp.float32, **cfg)
        if data.dtype == jnp.float64:
            # f64 already accumulates wide; a narrower preferred raises
            return lax.conv_general_dilated(data, weight, **cfg)

    @jax.custom_vjp
    def conv(d, w):
        return lax.conv_general_dilated(
            d, w, preferred_element_type=jnp.float32,
            **cfg).astype(d.dtype)

    def fwd(d, w):
        return conv(d, w), (d, w)

    def bwd(res, g):
        d, w = res
        _, vjp = jax.vjp(
            lambda d_, w_: lax.conv_general_dilated(d_, w_, **cfg), d, w)
        return vjp(g.astype(d.dtype))

    conv.defvjp(fwd, bwd)
    return conv(data, weight)


# -- linear --------------------------------------------------------------------

@register("FullyConnected", aliases=("fully_connected",))
def fully_connected(data, weight, bias=None, num_hidden=None, no_bias=False,
                    flatten=True):
    """y = x W^T + b.  Weight layout (num_hidden, in) matches the reference
    (src/operator/nn/fully_connected.cc).  The contraction is a single MXU
    matmul; accumulate in f32 when inputs are bf16."""
    if flatten and data.ndim > 2:
        data = data.reshape(data.shape[0], -1)
    out = lax.dot_general(
        data, weight,
        (((data.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(data.dtype)
    if bias is not None and not no_bias:
        out = out + bias
    return out


# -- activations ---------------------------------------------------------------

_ACTS = {
    "relu": lambda x: jnp.maximum(x, 0),
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "softrelu": jax.nn.softplus,
    "softsign": lambda x: x / (1 + jnp.abs(x)),
    "gelu": jax.nn.gelu,
    "silu": jax.nn.silu,
    "swish": jax.nn.silu,
    "erf": jax.scipy.special.erf,
}


@register("Activation", aliases=("activation",))
def activation(data, act_type="relu"):
    return _ACTS[act_type](data)


@register("LeakyReLU", aliases=("leaky_relu",))
def leaky_relu(data, gamma=None, act_type="leaky", slope=0.25,
               lower_bound=0.125, upper_bound=0.334):
    if act_type == "leaky":
        return jnp.where(data >= 0, data, slope * data)
    if act_type == "prelu":
        g = gamma
        if g.ndim < data.ndim and data.ndim > 1:
            shape = [1] * data.ndim
            shape[1] = g.size if g.size > 1 else 1
            g = g.reshape(shape) if g.size > 1 else g.reshape([1] * data.ndim)
        return jnp.where(data >= 0, data, g * data)
    if act_type == "elu":
        return jnp.where(data >= 0, data, slope * (jnp.exp(data) - 1))
    if act_type == "selu":
        alpha, scale = 1.6732632423543772, 1.0507009873554805
        return scale * jnp.where(data >= 0, data, alpha * (jnp.exp(data) - 1))
    if act_type == "rrelu":
        mid = (lower_bound + upper_bound) / 2.0
        return jnp.where(data >= 0, data, mid * data)
    raise ValueError(f"unknown LeakyReLU act_type {act_type}")


# -- softmax family ------------------------------------------------------------

@register("softmax")
def softmax(data, axis=-1, temperature=None, length=None):
    if temperature is not None and temperature != 1.0:
        data = data / temperature
    if length is not None:
        steps = jnp.arange(data.shape[axis])
        shape = [1] * data.ndim
        shape[axis] = data.shape[axis]
        mask = steps.reshape(shape) < length.reshape(
            [-1] + [1] * (data.ndim - 1))
        data = jnp.where(mask, data, -jnp.inf)
    return jax.nn.softmax(data, axis=axis)


@register("log_softmax")
def log_softmax(data, axis=-1, temperature=None):
    if temperature is not None and temperature != 1.0:
        data = data / temperature
    return jax.nn.log_softmax(data, axis=axis)


@register("softmin")
def softmin(data, axis=-1):
    return jax.nn.softmax(-data, axis=axis)


@register("softmax_cross_entropy")
def softmax_cross_entropy(data, label):
    logp = jax.nn.log_softmax(data, axis=-1)
    nll = -jnp.take_along_axis(
        logp, label.astype(jnp.int32)[:, None], axis=-1)
    return jnp.sum(nll)


from functools import partial


@partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6))
def _softmax_output_core(data, label, grad_scale, ignore_label, use_ignore,
                         normalization, smooth_alpha):
    return jax.nn.softmax(data, axis=-1)


def _softmax_output_fwd(data, label, grad_scale, ignore_label, use_ignore,
                        normalization, smooth_alpha):
    out = jax.nn.softmax(data, axis=-1)
    return out, (out, label)


def _softmax_output_bwd(grad_scale, ignore_label, use_ignore, normalization,
                        smooth_alpha, res, g):
    # Reference semantics (src/operator/nn/softmax_output.cc): backward
    # ignores the incoming gradient and emits grad_scale * (p - onehot(y)),
    # optionally masking ignored labels, normalized per `normalization`
    # ('null' = none, 'batch' = /batch, 'valid' = /non-ignored count).
    out, label = res
    classes = out.shape[-1]
    ilabel = label.astype(jnp.int32)
    onehot = jax.nn.one_hot(ilabel, classes, dtype=out.dtype)
    if smooth_alpha:
        onehot = onehot * (1.0 - smooth_alpha) + smooth_alpha / classes
    grad = out - onehot
    valid = None
    if use_ignore:
        mask = (ilabel != int(ignore_label)).astype(out.dtype)
        grad = grad * mask[..., None]
        valid = jnp.maximum(jnp.sum(mask), 1.0)
    if normalization == "batch":
        grad = grad / out.shape[0]
    elif normalization == "valid":
        if valid is None:
            valid = jnp.asarray(float(_np_prod(out.shape[:-1])))
        grad = grad / valid
    return grad_scale * grad, jnp.zeros_like(label)


def _np_prod(shape):
    p = 1
    for s in shape:
        p *= s
    return p


_softmax_output_core.defvjp(_softmax_output_fwd, _softmax_output_bwd)


@register("SoftmaxOutput", aliases=("softmax_output",))
def softmax_output(data, label, grad_scale=1.0, ignore_label=-1,
                   use_ignore=False, multi_output=False,
                   preserve_shape=False, normalization="null",
                   out_grad=False, smooth_alpha=0.0):
    return _softmax_output_core(data, label, float(grad_scale),
                                int(ignore_label), bool(use_ignore),
                                str(normalization), float(smooth_alpha))


# -- convolution ---------------------------------------------------------------

_DEFAULT_CONV_LAYOUT = {3: "NCW", 4: "NCHW", 5: "NCDHW"}


def _conv_dn(ndim, layout=None):
    """Dimension-number spec for a given data layout.

    The WEIGHT layout is always OI+spatial regardless of data layout —
    parameters stay layout-portable (an NCHW checkpoint loads into an
    NHWC model unchanged); XLA relayouts for the MXU internally.  The
    reference's NHWC conv instead expects NHWC weights
    (src/operator/nn/convolution.cc layout switch) — divergence is
    deliberate and documented in docs/perf.md.
    """
    lhs = layout or _DEFAULT_CONV_LAYOUT[ndim]
    if len(lhs) != ndim or set("NC") - set(lhs):
        raise ValueError(f"bad conv layout {lhs!r} for {ndim}d data")
    rhs = "OI" + "".join(c for c in lhs if c not in "NC")
    return (lhs, rhs, lhs)


def _channel_pos(ndim, layout):
    return (layout or _DEFAULT_CONV_LAYOUT[ndim]).index("C")


@register("Convolution", aliases=("convolution",))
def convolution(data, weight, bias=None, kernel=None, stride=None, dilate=None,
                pad=None, num_filter=None, num_group=1, no_bias=False,
                cudnn_tune=None, cudnn_off=False, workspace=1024, layout=None):
    """Grouped N-D convolution, NCHW/OIHW (reference layout) or
    channels-last via ``layout`` ("NHWC"/"NWC"/"NDHWC"; weights stay
    OI+spatial — see _conv_dn).

    XLA maps this to the MXU; bf16 inputs accumulate in f32 via
    preferred_element_type (the TPU-native analog of cuDNN tensor-core math).
    """
    nd = data.ndim
    spatial = nd - 2
    stride = _pair(stride or 1, spatial)
    dilate = _pair(dilate or 1, spatial)
    pad = _pair(pad or 0, spatial)
    dn = lax.conv_dimension_numbers(data.shape, weight.shape,
                                    _conv_dn(nd, layout))
    out = _conv_f32_accum(
        data, weight,
        window_strides=stride,
        padding=[(p, p) for p in pad],
        rhs_dilation=dilate,
        dimension_numbers=dn,
        feature_group_count=num_group,
    ).astype(data.dtype)
    if bias is not None and not no_bias:
        bshape = [1] * nd
        bshape[_channel_pos(nd, layout)] = -1
        out = out + bias.reshape(bshape)
    return out


@register("Deconvolution", aliases=("deconvolution",))
def deconvolution(data, weight, bias=None, kernel=None, stride=None,
                  dilate=None, pad=None, adj=None, target_shape=None,
                  num_filter=None, num_group=1, no_bias=True, workspace=512,
                  cudnn_tune=None, cudnn_off=False, layout=None):
    """Transposed convolution (reference: src/operator/nn/deconvolution.cc).
    Weight layout (in, out/group, *k) as in the reference."""
    nd = data.ndim
    spatial = nd - 2
    stride = _pair(stride or 1, spatial)
    dilate = _pair(dilate or 1, spatial)
    pad = _pair(pad or 0, spatial)
    adj = _pair(adj or 0, spatial)
    kshape = weight.shape[2:]
    cpos = _channel_pos(nd, layout)
    # conv_transpose padding that inverts a forward conv with `pad`:
    padding = []
    for k, p, a, d in zip(kshape, pad, adj, dilate):
        keff = (k - 1) * d + 1
        padding.append((keff - 1 - p, keff - 1 - p + a))
    if num_group != 1:
        groups_in = jnp.split(data, num_group, axis=cpos)
        groups_w = jnp.split(weight, num_group, axis=0)
        outs = [_deconv_one(x, w, stride, padding, dilate, layout)
                for x, w in zip(groups_in, groups_w)]
        out = jnp.concatenate(outs, axis=cpos)
    else:
        out = _deconv_one(data, weight, stride, padding, dilate, layout)
    if bias is not None and not no_bias:
        bshape = [1] * nd
        bshape[cpos] = -1
        out = out + bias.reshape(bshape)
    return out


def _deconv_one(data, weight, stride, padding, dilate, layout=None):
    nd = data.ndim
    # lhs_dilation implements the fractional stride of conv_transpose.
    w = jnp.flip(weight, axis=tuple(range(2, nd)))
    w = jnp.swapaxes(w, 0, 1)  # IO* -> OI* for the underlying conv
    dn2 = lax.conv_dimension_numbers(data.shape, w.shape,
                                     _conv_dn(nd, layout))
    return _conv_f32_accum(
        data, w,
        window_strides=(1,) * (nd - 2),
        padding=padding,
        lhs_dilation=stride,
        rhs_dilation=dilate,
        dimension_numbers=dn2,
    ).astype(data.dtype)


# -- pooling -------------------------------------------------------------------

@register("Pooling", aliases=("pooling",))
def pooling(data, kernel=None, pool_type="max", global_pool=False,
            stride=None, pad=None, pooling_convention="valid",
            count_include_pad=True, cudnn_off=False, layout=None, p_value=2):
    spatial = data.ndim - 2
    cpos = _channel_pos(data.ndim, layout)
    sp_axes = tuple(i for i in range(1, data.ndim) if i != cpos)
    if global_pool:
        axes = sp_axes
        if pool_type == "max":
            return jnp.max(data, axis=axes, keepdims=True)
        if pool_type in ("avg", "sum"):
            red = jnp.sum if pool_type == "sum" else jnp.mean
            return red(data, axis=axes, keepdims=True)
        if pool_type == "lp":
            return jnp.power(
                jnp.sum(jnp.power(jnp.abs(data), p_value), axis=axes,
                        keepdims=True), 1.0 / p_value)
    kernel = _pair(kernel, spatial)
    stride = _pair(stride or 1, spatial)
    pad = _pair(pad or 0, spatial)
    window = [1] * data.ndim
    strides = [1] * data.ndim
    for ax, k, s in zip(sp_axes, kernel, stride):
        window[ax], strides[ax] = k, s
    window, strides = tuple(window), tuple(strides)
    if pooling_convention == "full":
        # ceil-mode: pad up so that ceil((x + 2p - k)/s) windows fit
        padding = []
        for ax, k, s, p in zip(sp_axes, kernel, stride, pad):
            x = data.shape[ax]
            out = -(-(x + 2 * p - k) // s) + 1  # ceil division
            needed = max((out - 1) * s + k - x - p, p)
            padding.append((p, needed))
    else:
        padding = [(p, p) for p in pad]
    padconf = [(0, 0)] * data.ndim
    for ax, pp in zip(sp_axes, padding):
        padconf[ax] = pp
    if pool_type == "max":
        init = -jnp.inf if jnp.issubdtype(data.dtype, jnp.floating) else \
            jnp.iinfo(data.dtype).min
        return lax.reduce_window(data, init, lax.max, window, strides,
                                 padconf)
    if pool_type in ("avg", "sum"):
        summed = lax.reduce_window(data, 0.0, lax.add, window, strides,
                                   padconf)
        if pool_type == "sum":
            return summed
        if count_include_pad:
            denom = 1.0
            for k in kernel:
                denom *= k
            return summed / denom
        ones = jnp.ones_like(data)
        counts = lax.reduce_window(ones, 0.0, lax.add, window, strides,
                                   padconf)
        return summed / counts
    if pool_type == "lp":
        powed = lax.reduce_window(jnp.power(jnp.abs(data), p_value), 0.0,
                                  lax.add, window, strides, padconf)
        return jnp.power(powed, 1.0 / p_value)
    raise ValueError(f"unknown pool_type {pool_type}")


# -- normalization -------------------------------------------------------------

def _bn_train_core(data, g, beta, eps, axis):
    """Training-mode BN with a hand-written minimal-HBM-pass VJP.

    The naive jnp.mean + jnp.var + autodiff formulation costs ~6 full
    passes over the activation per layer (measured: 45 ms/step of
    reduce fusions on ResNet-50 b256 — the single largest line in the
    step profile).  This version is bandwidth-optimal:
      fwd: 1 fused read (sum & sumsq together, f32 accumulation) +
           1 read/write (normalize, fused with whatever follows)
      bwd: 1 fused read of (x, dy) for the two sums +
           1 read of (x, dy) / write of dx
    Stats math is f32 regardless of activation dtype (reference keeps
    BN stats fp32, src/operator/nn/batch_norm.cc).
    """
    axes = tuple(i for i in range(data.ndim) if i != axis)
    shape = [1] * data.ndim
    shape[axis] = data.shape[axis]
    n = 1.0
    for i in axes:
        n *= data.shape[i]

    @jax.custom_vjp
    def bn(x, gg, bb):
        out, mean, var, _inv = _fwd_math(x, gg, bb)
        return out, mean, var

    def _fwd_math(x, gg, bb):
        xf = x.astype(jnp.float32)
        s1 = jnp.sum(xf, axis=axes)
        s2 = jnp.sum(xf * xf, axis=axes)
        mean = s1 / n
        var = jnp.maximum(s2 / n - mean * mean, 0.0)
        inv = lax.rsqrt(var + eps)
        scale = (gg.astype(jnp.float32) * inv).reshape(shape)
        shift = (bb.astype(jnp.float32)
                 - gg.astype(jnp.float32) * inv * mean).reshape(shape)
        out = (xf * scale + shift).astype(x.dtype)
        return out, mean, var, inv

    def bn_fwd(x, gg, bb):
        out, mean, var, inv = _fwd_math(x, gg, bb)
        return (out, mean, var), (x, gg, mean, inv)

    def bn_bwd(res, cts):
        x, gg, mean, inv = res
        dy, dmean, dvar = cts
        xf = x.astype(jnp.float32)
        dyf = dy.astype(jnp.float32)
        mean_b = mean.reshape(shape)
        inv_b = inv.reshape(shape)
        xhat = (xf - mean_b) * inv_b
        sum_dy = jnp.sum(dyf, axis=axes)
        sum_dy_xhat = jnp.sum(dyf * xhat, axis=axes)
        gf = gg.astype(jnp.float32)
        coef = (gf * inv).reshape(shape)
        dx = coef * (dyf - (sum_dy / n).reshape(shape)
                     - xhat * (sum_dy_xhat / n).reshape(shape))
        # cotangents on the mean/var outputs themselves (a loss reading
        # the batch statistics): d mean/dx = 1/n, d var/dx = 2(x-mean)/n
        if dmean is not None:
            dx = dx + (dmean.astype(jnp.float32) / n).reshape(shape)
        if dvar is not None:
            dx = dx + (dvar.astype(jnp.float32) / n).reshape(shape) \
                * 2.0 * (xf - mean_b)
        return (dx.astype(x.dtype), sum_dy_xhat.astype(gg.dtype),
                sum_dy.astype(gg.dtype))

    bn.defvjp(bn_fwd, bn_bwd)
    return bn(data, g, beta)


@register("BatchNorm", aliases=("batch_norm",), mode_dependent=True)
def batch_norm(data, gamma, beta, moving_mean, moving_var, eps=1e-5,
               momentum=0.9, fix_gamma=True, use_global_stats=False,
               output_mean_var=False, axis=1, cudnn_off=False,
               _is_training=True):
    """Functional BatchNorm.  In train mode normalizes with batch statistics
    and returns (out, batch_mean, batch_var) when output_mean_var — the gluon
    layer owns the moving-average update (the reference mutates aux states
    in-kernel, src/operator/nn/batch_norm.cc)."""
    shape = [1] * data.ndim
    shape[axis] = data.shape[axis]
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    if _is_training and not use_global_stats:
        out, mean, var = _bn_train_core(data, g, beta, eps, axis)
        if output_mean_var:
            # stats in the aux dtype so the moving-average update doesn't
            # drift the running buffers' dtype across steps
            return (out, mean.astype(moving_mean.dtype),
                    var.astype(moving_var.dtype))
        return out
    mean, var = moving_mean, moving_var
    inv = lax.rsqrt(var.astype(jnp.float32) + eps).reshape(shape)
    meanf = mean.astype(jnp.float32).reshape(shape)
    out = ((data.astype(jnp.float32) - meanf) * inv
           * g.astype(jnp.float32).reshape(shape)
           + beta.astype(jnp.float32).reshape(shape)).astype(data.dtype)
    if output_mean_var:
        return out, mean, var
    return out


@register("LayerNorm", aliases=("layer_norm",))
def layer_norm(data, gamma, beta, axis=-1, eps=1e-5, output_mean_var=False):
    mean = jnp.mean(data, axis=axis, keepdims=True)
    var = jnp.var(data, axis=axis, keepdims=True)
    inv = lax.rsqrt(var + eps)
    shape = [1] * data.ndim
    shape[axis] = data.shape[axis]
    out = (data - mean) * inv * gamma.reshape(shape) + beta.reshape(shape)
    if output_mean_var:
        return out, jnp.squeeze(mean, axis), jnp.squeeze(var, axis)
    return out


@register("RMSNorm", aliases=("rms_norm",))
def rms_norm(data, gamma, axis=-1, eps=1e-6):
    ms = jnp.mean(jnp.square(data), axis=axis, keepdims=True)
    shape = [1] * data.ndim
    shape[axis] = data.shape[axis]
    return data * lax.rsqrt(ms + eps) * gamma.reshape(shape)


@register("InstanceNorm", aliases=("instance_norm",))
def instance_norm(data, gamma, beta, eps=1e-3):
    axes = tuple(range(2, data.ndim))
    mean = jnp.mean(data, axis=axes, keepdims=True)
    var = jnp.var(data, axis=axes, keepdims=True)
    shape = (1, -1) + (1,) * (data.ndim - 2)
    return (data - mean) * lax.rsqrt(var + eps) * gamma.reshape(shape) \
        + beta.reshape(shape)


@register("GroupNorm", aliases=("group_norm",))
def group_norm(data, gamma, beta, num_groups=1, eps=1e-5):
    n, c = data.shape[:2]
    spatial = data.shape[2:]
    x = data.reshape((n, num_groups, c // num_groups) + spatial)
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    x = (x - mean) * lax.rsqrt(var + eps)
    x = x.reshape(data.shape)
    shape = (1, -1) + (1,) * (data.ndim - 2)
    return x * gamma.reshape(shape) + beta.reshape(shape)


# -- dropout -------------------------------------------------------------------

@register("Dropout", aliases=("dropout",), mode_dependent=True, random=True)
def dropout(data, p=0.5, mode="training", axes=(), cudnn_off=False,
            _is_training=True, _key=None):
    if not _is_training and mode != "always":
        return data
    if p <= 0.0:
        return data
    keep = 1.0 - p
    shape = list(data.shape)
    for a in axes:
        shape[a] = 1  # broadcast dropout along these axes
    mask = jax.random.bernoulli(_key, keep, tuple(shape))
    return jnp.where(mask, data / keep, 0.0).astype(data.dtype)


# -- resize / upsample ---------------------------------------------------------

@register("UpSampling", aliases=("upsampling",))
def upsampling(data, scale=2, sample_type="nearest", num_filter=0,
               multi_input_mode="concat", workspace=512):
    n, c, h, w = data.shape
    if sample_type == "nearest":
        return jnp.repeat(jnp.repeat(data, scale, axis=2), scale, axis=3)
    return jax.image.resize(data, (n, c, h * scale, w * scale), "bilinear")


@register("BilinearResize2D", aliases=("bilinear_resize_2d",))
def bilinear_resize_2d(data, like=None, height=1, width=1, scale_height=None,
                       scale_width=None, mode="size", align_corners=True):
    """Reference: src/operator/contrib/bilinear_resize.cc — mode selects how
    the output size is derived (size / scale / odd_scale / like / to_even_*)."""
    n, c, h, w = data.shape
    if mode == "like" and like is not None:
        height, width = like.shape[-2], like.shape[-1]
    elif scale_height is not None:
        sw = scale_width if scale_width is not None else scale_height
        height, width = int(h * scale_height), int(w * sw)
        if mode == "odd_scale":
            height += (height + 1) % 2
            width += (width + 1) % 2
    elif mode == "to_even_down":
        height, width = h - h % 2, w - w % 2
    elif mode == "to_even_up":
        height, width = h + h % 2, w + w % 2
    elif mode == "to_odd_down":
        height, width = h - (h + 1) % 2, w - (w + 1) % 2
    elif mode == "to_odd_up":
        height, width = h + (h + 1) % 2, w + (w + 1) % 2
    return jax.image.resize(data, (n, c, height, width), "bilinear")


# -- misc ----------------------------------------------------------------------

@register("Custom", opaque=True)
def custom(*data, op_type=None, **kwargs):
    """Reference: src/operator/custom/custom.cc — python callback ops.
    Dispatches to the CustomOp registry (mxnet_tpu.operator)."""
    from .. import operator as custom_mod

    return custom_mod._invoke_custom(op_type, data, kwargs)


@register("Cast_storage", aliases=("cast_storage",))
def cast_storage(data, stype="default"):
    # Sparse storage types are represented densely on TPU (XLA has no sparse
    # layout); kept for API parity.
    return data
