"""Operator registry.

Reference parity: the nnvm Op registry (NNVM_REGISTER_OP + FCompute/FGradient,
reference: 3rdparty/nnvm include/nnvm/op.h, src/operator/**) and the
import-time Python wrapper generation (python/mxnet/ndarray/register.py).

TPU-first redesign: an op is a *pure JAX function* — shape/type inference,
memory planning, kernel selection and fusion all belong to XLA, so the
registry stores only the function plus frontend metadata.  Gradients come
from JAX autodiff (``jax.vjp``), replacing the FGradient registry; ops that
need custom gradients use ``jax.custom_vjp`` inside their implementation.

Every registered op gets a generated NDArray-aware wrapper (see
``mxnet_tpu.ndarray.register``).  Wrappers are polymorphic: called with
NDArrays they run the eager path (unwrap → compute → wrap, recording on the
autograd tape when active); called with jax arrays/tracers (e.g. inside a
``hybridize()`` trace) they pass straight through to the pure function.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

from ..base import MXNetError


@dataclasses.dataclass
class OpDef:
    name: str
    fn: Callable
    aliases: tuple = ()
    # Ops whose semantics depend on train vs predict mode (Dropout, BatchNorm):
    # the wrapper injects _is_training from the autograd scope when unset.
    mode_dependent: bool = False
    # Ops that consume randomness: the wrapper injects a PRNG key kwarg
    # (named _key) from the global/random key scope when unset.
    random: bool = False
    # Opaque ops run on NDArrays directly (host-level, own tape handling —
    # e.g. Custom); the invoke layer must not unwrap or jax.vjp them.
    opaque: bool = False


_OPS: dict[str, OpDef] = {}


def register(name: str | None = None, aliases: tuple = (),
             mode_dependent: bool = False, random: bool = False,
             opaque: bool = False):
    """Decorator registering a pure-JAX op under its reference name."""

    def _do(fn):
        opname = name or fn.__name__
        opdef = OpDef(opname, fn, tuple(aliases), mode_dependent, random,
                      opaque)
        if opname in _OPS:
            raise MXNetError(f"op {opname!r} registered twice")
        _OPS[opname] = opdef
        for a in opdef.aliases:
            _OPS.setdefault(a, OpDef(a, fn, (), mode_dependent, random,
                                     opaque))
        return fn

    return _do


def get(name: str) -> OpDef:
    if name not in _OPS:
        raise MXNetError(f"op {name!r} not registered")
    return _OPS[name]


def list_ops() -> list[str]:
    """All registered op names (reference: MXListAllOpNames)."""
    return sorted(_OPS)


def all_ops() -> dict[str, OpDef]:
    return dict(_OPS)
