"""Mixture-of-Experts ops (Switch/GShard-style sparse FFN).

NEW, TPU-first (SURVEY.md §2.5 scoped expert parallelism out of v1; this
closes it): the reference has no MoE — the design here follows the
public GShard/Switch recipe that TPU systems use, because it is the
shape XLA compiles well: capacity-based DENSE dispatch (einsum with a
(tokens, experts, capacity) one-hot) instead of data-dependent gather —
static shapes, MXU-friendly, and under a mesh the expert dimension of
the weights shards over the ``ep`` axis so GSPMD inserts the
token↔expert all-to-alls from annotations alone.

Capacity semantics match Switch Transformers: each expert processes at
most ``ceil(tokens/experts · capacity_factor)`` tokens; overflow tokens
pass through the residual (combine weight 0).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .registry import register


def _top1_dispatch(probs, capacity, base_counts):
    """probs: (N, E) → dispatch (N, E, C) one-hot, combine (N, E, C).

    ``base_counts`` (E,) is the number of slots each expert already has
    occupied by earlier top-1 rounds; this round's queue positions start
    after them (GShard: second-choice positions begin after all kept
    first-choice tokens), so rounds never collide on a capacity slot.
    Also returns the updated per-expert occupied-slot counts and this
    round's (N, E) selection one-hot (the caller masks with it).
    """
    n, e = probs.shape
    gate = jnp.max(probs, axis=1)                      # (N,)
    idx = jnp.argmax(probs, axis=1)                    # (N,)
    sel = jax.nn.one_hot(idx, e, dtype=probs.dtype)    # (N, E)
    # position of each token within its expert's queue, offset by the
    # slots earlier rounds already filled
    pos = (jnp.cumsum(sel, axis=0) - 1.0 + base_counts[None, :]) * sel
    pos_tok = jnp.sum(pos, axis=1)                     # (N,)
    keep = pos_tok < capacity
    gate = gate * keep.astype(probs.dtype)
    dispatch = sel[:, :, None] * jax.nn.one_hot(
        pos_tok, capacity, dtype=probs.dtype)[:, None, :]
    dispatch = dispatch * keep[:, None, None].astype(probs.dtype)
    combine = dispatch * gate[:, None, None]
    new_counts = base_counts + jnp.sum(
        sel * keep[:, None].astype(probs.dtype), axis=0)
    return dispatch, combine, new_counts, sel


@register("moe_ffn", aliases=("MoEFFN_op",))
def moe_ffn(data, gate_weight, w1, b1, w2, b2, num_experts=None, k=1,
            capacity_factor=1.25, activation="relu",
            output_aux_loss=False):
    """Sparse MoE FFN: route → dispatch → per-expert FFN → combine.

    data: (..., M); gate_weight: (E, M) (FullyConnected layout);
    w1: (E, M, F); b1: (E, F); w2: (E, F, M); b2: (E, M).
    Returns y (same shape as data); with output_aux_loss also returns
    the Switch load-balancing loss  E · Σ_e f_e · p̄_e  (scalar).
    """
    orig_shape = data.shape
    m = orig_shape[-1]
    x = data.reshape(-1, m)
    n = x.shape[0]
    e = gate_weight.shape[0]
    capacity = max(1, int(math.ceil(n / e * capacity_factor)))

    logits = jnp.einsum("nm,em->ne", x.astype(jnp.float32),
                        gate_weight.astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)

    dispatch = jnp.zeros((n, e, capacity), probs.dtype)
    combine = jnp.zeros((n, e, capacity), probs.dtype)
    masked = probs
    counts = jnp.zeros((e,), probs.dtype)
    for _ in range(int(k)):
        d_i, c_i, counts, sel_i = _top1_dispatch(masked, capacity, counts)
        dispatch = jnp.maximum(dispatch, d_i)
        combine = combine + c_i
        # mask out the chosen expert for the next pick (by argmax
        # selection, not by kept slot — a dropped token must not re-pick
        # the same, full expert)
        masked = masked * (1.0 - sel_i)
    if k > 1:
        # renormalize combine weights over the k picks (GShard top-2)
        denom = jnp.sum(combine, axis=(1, 2), keepdims=True)
        combine = combine / jnp.maximum(denom, 1e-9)

    dispatch = dispatch.astype(data.dtype)
    combine = combine.astype(data.dtype)

    expert_in = jnp.einsum("nec,nm->ecm", dispatch, x)
    h = jnp.einsum("ecm,emf->ecf", expert_in, w1,
                   preferred_element_type=jnp.float32).astype(data.dtype)
    h = h + b1[:, None, :]
    if activation == "relu":
        h = jnp.maximum(h, 0)
    elif activation == "gelu":
        h = jax.nn.gelu(h)
    out_e = jnp.einsum("ecf,efm->ecm", h, w2,
                       preferred_element_type=jnp.float32) \
        .astype(data.dtype)
    out_e = out_e + b2[:, None, :]
    y = jnp.einsum("nec,ecm->nm", combine, out_e).reshape(orig_shape)

    if not output_aux_loss:
        return y
    # Switch aux loss: fraction of tokens per expert × mean router prob
    sel1 = jax.nn.one_hot(jnp.argmax(probs, axis=1), e,
                          dtype=jnp.float32)
    f = jnp.mean(sel1, axis=0)
    p = jnp.mean(probs, axis=0)
    aux = e * jnp.sum(f * p)
    return y, aux.astype(data.dtype)
