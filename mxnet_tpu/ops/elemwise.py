"""Elementwise unary/binary ops.

Reference parity: src/operator/tensor/elemwise_unary_op_basic.cc,
elemwise_binary_op_basic.cc, src/operator/mshadow_op.h (the functor zoo).
All fuse trivially under XLA; nothing here needs Pallas.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register


def _u(name, f, aliases=()):
    register(name, aliases=aliases)(f)
    return f


# -- unary ---------------------------------------------------------------------
_u("abs", jnp.abs)
_u("sign", jnp.sign)
_u("ceil", jnp.ceil)
_u("floor", jnp.floor)
_u("trunc", jnp.trunc)
_u("rint", jnp.rint)
_u("fix", jnp.trunc)
_u("round", jnp.round)
_u("exp", jnp.exp)
_u("expm1", jnp.expm1)
_u("log", jnp.log)
_u("log2", jnp.log2)
_u("log10", jnp.log10)
_u("log1p", jnp.log1p)
_u("sqrt", jnp.sqrt)
_u("square", jnp.square)
_u("cbrt", jnp.cbrt)
_u("negative", jnp.negative)
_u("sin", jnp.sin)
_u("cos", jnp.cos)
_u("tan", jnp.tan)
_u("arcsin", jnp.arcsin)
_u("arccos", jnp.arccos)
_u("arctan", jnp.arctan)
_u("sinh", jnp.sinh)
_u("cosh", jnp.cosh)
_u("tanh", jnp.tanh)
_u("arcsinh", jnp.arcsinh)
_u("arccosh", jnp.arccosh)
_u("arctanh", jnp.arctanh)
_u("degrees", jnp.degrees)
_u("radians", jnp.radians)
_u("erf", jax.scipy.special.erf)
_u("erfinv", jax.scipy.special.erfinv)
_u("gamma", lambda x: jnp.exp(jax.scipy.special.gammaln(x)))
_u("gammaln", jax.scipy.special.gammaln)
_u("logical_not", lambda x: jnp.logical_not(x).astype(jnp.float32))
_u("isnan", jnp.isnan)
_u("isinf", jnp.isinf)
_u("isfinite", jnp.isfinite)


@register("reciprocal")
def reciprocal(data):
    return 1.0 / data


@register("rsqrt")
def rsqrt(data):
    return jax.lax.rsqrt(data)


@register("rcbrt")
def rcbrt(data):
    return 1.0 / jnp.cbrt(data)


@register("relu")
def relu(data):
    return jnp.maximum(data, 0)


@register("sigmoid")
def sigmoid(data):
    return jax.nn.sigmoid(data)


@register("hard_sigmoid")
def hard_sigmoid(data, alpha=0.2, beta=0.5):
    return jnp.clip(alpha * data + beta, 0.0, 1.0)


@register("softsign")
def softsign(data):
    return data / (1.0 + jnp.abs(data))


@register("softrelu")
def softrelu(data):
    return jax.nn.softplus(data)


@register("gelu")
def gelu(data, approximate=True):
    return jax.nn.gelu(data, approximate=approximate)


@register("silu", aliases=("swish",))
def silu(data):
    return jax.nn.silu(data)


@register("clip")
def clip(data, a_min=None, a_max=None):
    return jnp.clip(data, a_min, a_max)


# -- binary (same-shape "elemwise_*" and broadcasting "broadcast_*") -----------
# XLA broadcasts natively, so the elemwise_* and broadcast_* families share
# implementations; the elemwise_* names are kept for reference-API parity.

def _b(name, f, aliases=()):
    register(name, aliases=aliases)(f)
    return f


_b("elemwise_add", jnp.add, aliases=("broadcast_add", "broadcast_plus", "add"))
_b("elemwise_sub", jnp.subtract,
   aliases=("broadcast_sub", "broadcast_minus", "subtract"))
_b("elemwise_mul", jnp.multiply, aliases=("broadcast_mul", "multiply"))
_b("elemwise_div", jnp.divide, aliases=("broadcast_div", "divide"))
_b("broadcast_mod", jnp.mod, aliases=("mod",))
_b("broadcast_power", jnp.power, aliases=("power", "pow"))
_b("broadcast_maximum", jnp.maximum, aliases=("maximum",))
_b("broadcast_minimum", jnp.minimum, aliases=("minimum",))
_b("broadcast_hypot", jnp.hypot, aliases=("hypot",))


def _cmp(f):
    return lambda lhs, rhs: f(lhs, rhs).astype(jnp.float32)


# Comparison ops return float32 0/1 masks, matching the reference
# (src/operator/tensor/elemwise_binary_broadcast_op_logic.cc).
_b("broadcast_equal", _cmp(jnp.equal), aliases=("equal",))
_b("broadcast_not_equal", _cmp(jnp.not_equal), aliases=("not_equal",))
_b("broadcast_greater", _cmp(jnp.greater), aliases=("greater",))
_b("broadcast_greater_equal", _cmp(jnp.greater_equal),
   aliases=("greater_equal",))
_b("broadcast_lesser", _cmp(jnp.less), aliases=("lesser", "less"))
_b("broadcast_lesser_equal", _cmp(jnp.less_equal),
   aliases=("lesser_equal", "less_equal"))
_b("broadcast_logical_and", _cmp(jnp.logical_and), aliases=("logical_and",))
_b("broadcast_logical_or", _cmp(jnp.logical_or), aliases=("logical_or",))
_b("broadcast_logical_xor", _cmp(jnp.logical_xor), aliases=("logical_xor",))


@register("smooth_l1")
def smooth_l1(data, scalar=1.0):
    s2 = scalar * scalar
    absd = jnp.abs(data)
    return jnp.where(absd < 1.0 / s2, 0.5 * s2 * data * data,
                     absd - 0.5 / s2)


@register("digamma")
def digamma(data):
    """Reference: mshadow_op digamma (unary_op_gamma)."""
    import jax

    return jax.scipy.special.digamma(data)
