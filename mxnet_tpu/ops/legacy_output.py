"""Legacy output/loss-head ops.

Reference parity: src/operator/regression_output.cc
(LinearRegressionOutput, LogisticRegressionOutput, MAERegressionOutput),
svm_output.cc (SVMOutput), make_loss.cc (MakeLoss), and the AMP helpers
all_finite/multi_all_finite (contrib/all_finite.cc, ≥1.5).

These ops have *asymmetric* forward/backward semantics — the forward is
(near-)identity while the backward injects the loss gradient — so each is
a ``jax.custom_vjp`` (the reference registers explicit backward kernels
for the same reason).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .registry import register


def _head(fwd_fn, grad_fn):
    """Build an output head: forward = fwd_fn(data), d(data) =
    grad_fn(data, label) * grad_scale / batch, d(label) = 0."""

    @functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
    def op(data, label, grad_scale):
        return fwd_fn(data)

    def fwd(data, label, grad_scale):
        return fwd_fn(data), (data, label)

    def bwd(grad_scale, res, g):
        data, label = res
        scale = grad_scale / data.shape[0]
        # the reference ignores the incoming head gradient (treats the
        # output as the loss terminal); match that but keep g's dtype
        dd = (grad_fn(data, label) * scale).astype(data.dtype)
        return dd, jnp.zeros_like(label)

    op.defvjp(fwd, bwd)
    return op


_linreg = _head(lambda d: d, lambda d, l: d - l.reshape(d.shape))
_maereg = _head(lambda d: d, lambda d, l: jnp.sign(d - l.reshape(d.shape)))
_logreg = _head(jax.nn.sigmoid,
                lambda d, l: jax.nn.sigmoid(d) - l.reshape(d.shape))


@register("LinearRegressionOutput", aliases=("linear_regression_output",))
def linear_regression_output(data, label, grad_scale=1.0):
    """Identity forward; backward injects (pred - label)
    (reference: regression_output.cc)."""
    return _linreg(data, label, float(grad_scale))


@register("MAERegressionOutput", aliases=("mae_regression_output",))
def mae_regression_output(data, label, grad_scale=1.0):
    return _maereg(data, label, float(grad_scale))


@register("LogisticRegressionOutput",
          aliases=("logistic_regression_output",))
def logistic_regression_output(data, label, grad_scale=1.0):
    return _logreg(data, label, float(grad_scale))


@register("SVMOutput", aliases=("svm_output",))
def svm_output(data, label, margin=1.0, regularization_coefficient=1.0,
               use_linear=False):
    """Forward = identity; backward is the (squared) hinge-loss gradient
    (reference: svm_output.cc)."""

    @functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
    def op(data, label, margin, reg, linear):
        return data

    def fwd(data, label, margin, reg, linear):
        return data, (data, label)

    def bwd(margin, reg, linear, res, g):
        data, label = res
        n_class = data.shape[1]
        onehot = jax.nn.one_hot(label.astype(jnp.int32), n_class,
                                dtype=data.dtype)
        score_y = jnp.sum(data * onehot, axis=1, keepdims=True)
        viol = margin - (score_y - data)          # margin violation
        active = (viol > 0) & (onehot == 0)
        if linear:
            dwrong = jnp.where(active, reg, 0.0)
        else:
            dwrong = jnp.where(active, 2.0 * viol * reg, 0.0)
        dright = -jnp.sum(dwrong, axis=1, keepdims=True) * onehot
        return (dwrong + dright).astype(data.dtype), \
            jnp.zeros_like(label)

    op.defvjp(fwd, bwd)
    return op(data, label, float(margin),
              float(regularization_coefficient), bool(use_linear))


@register("MakeLoss", aliases=("make_loss",))
def make_loss(data, grad_scale=1.0, valid_thresh=0.0,
              normalization="null"):
    """Marks a symbol as a loss terminal: forward = identity, backward =
    grad_scale, normalized per ``normalization`` (reference:
    make_loss.cc):

    - ``'null'``  — d(data) = grad_scale
    - ``'batch'`` — d(data) = grad_scale / batch_size
    - ``'valid'`` — d(data) = grad_scale / #{elements > valid_thresh}
      (the reference counts valid loss entries in the DATA itself,
      clamped to >= 1)
    """

    shape, dtype = tuple(data.shape), data.dtype

    @functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
    def op(data, scale, norm, thresh):
        return data

    def fwd(data, scale, norm, thresh):
        # only 'valid' needs the values in the backward; 'null'/'batch'
        # use the closure shape so the loss tensor isn't held live
        return data, (data if norm == "valid" else None)

    def bwd(scale, norm, thresh, res, g):
        if norm == "valid":
            denom = jnp.maximum(
                jnp.sum(res > thresh).astype(jnp.float32), 1.0)
        else:
            denom = jnp.asarray(
                float(shape[0]) if norm == "batch" else 1.0, jnp.float32)
        # divide in f32: casting denom to f16 first overflows past 65504
        # valid elements (grad silently zero) and underflows tiny ratios
        return (jnp.full(shape, scale / denom, jnp.float32)
                .astype(dtype),)

    op.defvjp(fwd, bwd)
    if normalization not in ("null", "batch", "valid"):
        raise ValueError(f"MakeLoss: unknown normalization "
                         f"{normalization!r}")
    return op(data, float(grad_scale), normalization,
              float(valid_thresh))


@register("all_finite")
def all_finite(data, init_output=True):
    """1.0 if every element is finite (reference: contrib/all_finite.cc;
    the AMP loss-scaling overflow check)."""
    return jnp.all(jnp.isfinite(data)).astype(jnp.float32)


@register("multi_all_finite")
def multi_all_finite(*arrays, num_arrays=None, init_output=True):
    ok = jnp.asarray(True)
    for a in arrays:
        ok = ok & jnp.all(jnp.isfinite(a))
    return ok.astype(jnp.float32)
