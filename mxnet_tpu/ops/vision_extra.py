"""Vision ops beyond the basic conv/pool set.

Reference parity: src/operator/ — LRN (lrn.cc), BilinearSampler
(bilinear_sampler.cc), GridGenerator (grid_generator.cc),
SpatialTransformer (spatial_transformer.cc), Crop (crop.cc), Correlation
(correlation.cc), and src/operator/contrib/ — Proposal (proposal.cc),
MultiProposal (multi_proposal.cc), DeformableConvolution
(deformable_convolution.cc), PSROIPooling (psroi_pooling.cc).

TPU-first: all static-shape jnp programs.  The samplers express bilinear
gather as vectorized take + lerp (XLA fuses the gathers); deformable conv
builds sampled im2col columns and runs ONE MXU matmul; Proposal keeps the
reference's padded fixed-length output contract (SURVEY.md §7
dynamic-shape strategy) so it jits with static shapes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register


# -- LRN -----------------------------------------------------------------------

@register("LRN", aliases=("lrn",))
def lrn(data, alpha=1e-4, beta=0.75, knorm=2.0, nsize=5):
    """Local response normalization across channels (reference: lrn.cc;
    AlexNet-era).  out = x / (knorm + alpha/n * sum_local x^2)^beta."""
    sq = jnp.square(data)
    half = nsize // 2
    # sum over a window of channels via padded cumsum difference
    pad = [(0, 0), (half, half)] + [(0, 0)] * (data.ndim - 2)
    padded = jnp.pad(sq, pad)
    csum = jnp.cumsum(padded, axis=1)
    zero = jnp.zeros_like(csum[:, :1])
    csum = jnp.concatenate([zero, csum], axis=1)
    C = data.shape[1]
    local = csum[:, nsize:nsize + C] - csum[:, :C]
    return data * jnp.power(knorm + (alpha / nsize) * local, -beta)


# -- bilinear sampling family --------------------------------------------------

def _bilinear_gather(data, gx, gy):
    """Sample NCHW `data` at fractional pixel coords (B, Ho, Wo) with
    zero padding outside; returns (B, C, Ho, Wo)."""
    B, C, H, W = data.shape
    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    dx = gx - x0
    dy = gy - y0

    def tap(xi, yi):
        inb = ((xi >= 0) & (xi <= W - 1) & (yi >= 0)
               & (yi <= H - 1))
        xc = jnp.clip(xi, 0, W - 1).astype(jnp.int32)
        yc = jnp.clip(yi, 0, H - 1).astype(jnp.int32)
        flat = data.reshape(B, C, H * W)
        lin = (yc * W + xc).reshape(B, -1)  # (B, Ho*Wo)
        vals = jnp.take_along_axis(flat, lin[:, None, :], axis=2)
        vals = vals.reshape(B, C, *xi.shape[1:])
        return vals * inb[:, None].astype(data.dtype)

    w00 = ((1 - dx) * (1 - dy))[:, None]
    w01 = (dx * (1 - dy))[:, None]
    w10 = ((1 - dx) * dy)[:, None]
    w11 = (dx * dy)[:, None]
    return (tap(x0, y0) * w00 + tap(x0 + 1, y0) * w01
            + tap(x0, y0 + 1) * w10 + tap(x0 + 1, y0 + 1) * w11)


@register("BilinearSampler", aliases=("bilinear_sampler",))
def bilinear_sampler(data, grid, cudnn_off=False):
    """Sample `data` (B,C,H,W) at `grid` (B,2,Ho,Wo) of normalized
    [-1,1] (x, y) coords (reference: bilinear_sampler.cc)."""
    B, C, H, W = data.shape
    gx = (grid[:, 0] + 1.0) * (W - 1) / 2.0
    gy = (grid[:, 1] + 1.0) * (H - 1) / 2.0
    return _bilinear_gather(data, gx, gy)


@register("GridGenerator", aliases=("grid_generator",))
def grid_generator(data, transform_type="affine", target_shape=(0, 0)):
    """Generate a sampling grid (reference: grid_generator.cc).

    affine: data (B, 6) row-major 2x3 -> grid (B, 2, H, W).
    warp: data (B, 2, H, W) flow field -> grid of (x+fx, y+fy) normalized.
    """
    if transform_type == "affine":
        B = data.shape[0]
        H, W = int(target_shape[0]), int(target_shape[1])
        theta = data.reshape(B, 2, 3)
        ys = jnp.linspace(-1.0, 1.0, H)
        xs = jnp.linspace(-1.0, 1.0, W)
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones]).reshape(3, H * W)  # (3, HW)
        out = jnp.einsum("bij,jk->bik", theta, base)  # (B, 2, HW)
        return out.reshape(B, 2, H, W)
    if transform_type == "warp":
        B, _, H, W = data.shape
        ys = jnp.arange(H, dtype=data.dtype)
        xs = jnp.arange(W, dtype=data.dtype)
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        x = (data[:, 0] + gx) * 2.0 / max(W - 1, 1) - 1.0
        y = (data[:, 1] + gy) * 2.0 / max(H - 1, 1) - 1.0
        return jnp.stack([x, y], axis=1)
    raise ValueError(f"unknown transform_type {transform_type}")


@register("SpatialTransformer", aliases=("spatial_transformer",))
def spatial_transformer(data, loc, target_shape=(0, 0),
                        transform_type="affine",
                        sampler_type="bilinear", cudnn_off=False):
    """Affine spatial transformer = GridGenerator + BilinearSampler
    (reference: spatial_transformer.cc, Jaderberg et al. 2015)."""
    grid = grid_generator(loc, "affine", target_shape)
    return bilinear_sampler(data, grid)


@register("Crop", aliases=("crop",))
def crop_op(data, *like, offset=(0, 0), h_w=(0, 0), num_args=None,
            center_crop=False):
    """Legacy Crop (reference: crop.cc): crop NCHW `data` to `h_w` (or to
    the spatial shape of a second input) at `offset` / centered."""
    H, W = data.shape[2], data.shape[3]
    if like:
        th, tw = like[0].shape[2], like[0].shape[3]
    else:
        th, tw = int(h_w[0]) or H, int(h_w[1]) or W
    if center_crop:
        oy, ox = (H - th) // 2, (W - tw) // 2
    else:
        oy, ox = int(offset[0]), int(offset[1])
    return lax.dynamic_slice(
        data, (0, 0, oy, ox),
        (data.shape[0], data.shape[1], th, tw))


# -- Correlation (FlowNet) -----------------------------------------------------

@register("Correlation", aliases=("correlation",))
def correlation(data1, data2, kernel_size=1, max_displacement=1, stride1=1,
                stride2=1, pad_size=0, is_multiply=True):
    """Correlation layer (reference: correlation.cc, FlowNet).  Output
    channel (i,j) is the patch dot-product of data1 with data2 shifted by
    displacement (dy, dx) over the window [-max_d, max_d] step stride2."""
    B, C, H, W = data1.shape
    p = int(pad_size)
    d1 = jnp.pad(data1, [(0, 0), (0, 0), (p, p), (p, p)])
    d2 = jnp.pad(data2, [(0, 0), (0, 0), (p, p), (p, p)])
    md, s1, s2 = int(max_displacement), int(stride1), int(stride2)
    k = int(kernel_size)
    bk = k // 2
    disps = range(-md, md + 1, s2)
    Hp, Wp = H + 2 * p, W + 2 * p
    # valid output grid (reference: top extents shrink by max_d + bk)
    y0, x0 = md + bk, md + bk
    Ho = (Hp - 2 * (md + bk) - 1) // s1 + 1
    Wo = (Wp - 2 * (md + bk) - 1) // s1 + 1
    outs = []
    for dy in disps:
        for dx in disps:
            if is_multiply:
                prod = d1 * jnp.roll(d2, (-dy, -dx), axis=(2, 3))
            else:
                prod = jnp.abs(d1 - jnp.roll(d2, (-dy, -dx), axis=(2, 3)))
            # patch sum over the kernel window then mean over channels
            if k > 1:
                prod = lax.reduce_window(
                    prod, 0.0, lax.add, (1, 1, k, k), (1, 1, 1, 1),
                    "SAME")
            m = jnp.mean(prod, axis=1)  # (B, Hp, Wp)
            m = lax.slice(m, (0, y0, x0),
                          (B, y0 + (Ho - 1) * s1 + 1,
                           x0 + (Wo - 1) * s1 + 1), (1, s1, s1))
            outs.append(m)
    return jnp.stack(outs, axis=1)


# -- DeformableConvolution -----------------------------------------------------

@register("_contrib_DeformableConvolution",
          aliases=("DeformableConvolution", "deformable_convolution"))
def deformable_convolution(data, offset, weight, bias=None, kernel=None,
                           stride=(1, 1), dilate=(1, 1), pad=(0, 0),
                           num_filter=None, num_group=1,
                           num_deformable_group=1, no_bias=False,
                           workspace=1024, layout=None):
    """Deformable conv v1 (reference: contrib/deformable_convolution.cc,
    Dai et al. 2017).  Each kernel tap samples the input at its grid
    position PLUS a learned per-location offset, via bilinear
    interpolation; the sampled im2col columns feed one MXU matmul."""
    B, C, H, W = data.shape
    O, Cg, kh, kw = weight.shape
    sh, sw = (stride, stride) if isinstance(stride, int) else stride
    dh, dw = (dilate, dilate) if isinstance(dilate, int) else dilate
    ph, pw = (pad, pad) if isinstance(pad, int) else pad
    Ho = (H + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
    Wo = (W + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
    ndg = int(num_deformable_group)
    # base sampling grid per tap: (kh*kw, Ho, Wo)
    oy = jnp.arange(Ho) * sh - ph
    ox = jnp.arange(Wo) * sw - pw
    gy0, gx0 = jnp.meshgrid(oy.astype(data.dtype), ox.astype(data.dtype),
                            indexing="ij")
    cols = []
    off = offset.reshape(B, ndg, kh * kw, 2, Ho, Wo)
    cpg = C // ndg  # channels per deformable group
    for t in range(kh * kw):
        ky, kx = divmod(t, kw)
        group_cols = []
        for g in range(ndg):
            gy = gy0[None] + ky * dh + off[:, g, t, 0]
            gx = gx0[None] + kx * dw + off[:, g, t, 1]
            sub = data[:, g * cpg:(g + 1) * cpg]
            group_cols.append(_bilinear_gather(sub, gx, gy))
        cols.append(jnp.concatenate(group_cols, axis=1))  # (B,C,Ho,Wo)
    # (B, C*kh*kw, Ho*Wo) im2col with taps ordered (c, ky, kx) like the
    # reference weight layout (O, C/g, kh, kw)
    colmat = jnp.stack(cols, axis=2).reshape(B, C * kh * kw, Ho * Wo)
    wmat = weight.reshape(O, Cg * kh * kw)
    if num_group == 1:
        out = jnp.einsum("ok,bkn->bon", wmat, colmat)
    else:
        og = O // num_group
        colg = colmat.reshape(B, num_group, Cg * kh * kw, Ho * Wo)
        wg = wmat.reshape(num_group, og, Cg * kh * kw)
        out = jnp.einsum("gok,bgkn->bgon", wg, colg)
        out = out.reshape(B, O, Ho * Wo)
    out = out.reshape(B, O, Ho, Wo)
    if bias is not None and not no_bias:
        out = out + bias.reshape(1, -1, 1, 1)
    return out


# -- PSROIPooling --------------------------------------------------------------

@register("_contrib_PSROIPooling", aliases=("psroi_pooling",))
def psroi_pooling(data, rois, spatial_scale=1.0, output_dim=None,
                  pooled_size=7, group_size=0):
    """Position-sensitive ROI pooling (reference: contrib/psroi_pooling.cc,
    R-FCN).  data: (B, output_dim*g*g, H, W); rois: (R, 5)."""
    g = int(group_size) or int(pooled_size)
    P = int(pooled_size)
    od = int(output_dim)
    B, CC, H, W = data.shape

    def one_roi(roi):
        b = roi[0].astype(jnp.int32)
        x1, y1, x2, y2 = roi[1] * spatial_scale, roi[2] * spatial_scale, \
            roi[3] * spatial_scale, roi[4] * spatial_scale
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bin_w, bin_h = rw / P, rh / P
        img = jnp.take(data, b, axis=0)  # (CC, H, W)
        out = []
        for py in range(P):
            for px in range(P):
                gy, gx = py * g // P, px * g // P
                # average pool the bin via a fixed 2x2 sample grid
                sy = y1 + (py + jnp.asarray([0.25, 0.75])[:, None]) * bin_h
                sx = x1 + (px + jnp.asarray([0.25, 0.75])[None, :]) * bin_w
                syc = jnp.clip(sy, 0, H - 1)
                sxc = jnp.clip(sx, 0, W - 1)
                chan0 = (gy * g + gx) * od
                sub = lax.dynamic_slice(img, (chan0, 0, 0), (od, H, W))
                vals = _bilinear_gather(
                    sub[None],
                    jnp.broadcast_to(sxc, (2, 2))[None],
                    jnp.broadcast_to(syc, (2, 2))[None])[0]
                out.append(jnp.mean(vals, axis=(1, 2)))
        return jnp.stack(out, -1).reshape(od, P, P)

    return jax.vmap(one_roi)(rois)


# -- Proposal (RPN) ------------------------------------------------------------

def _make_anchors(feature_stride, scales, ratios):
    """Reference anchor generation (proposal.cc GenerateAnchors)."""
    import numpy as np

    base = np.array([1, 1, feature_stride, feature_stride]) - 1
    w = base[2] - base[0] + 1
    h = base[3] - base[1] + 1
    cx, cy = base[0] + 0.5 * (w - 1), base[1] + 0.5 * (h - 1)
    anchors = []
    for r in ratios:
        size = w * h
        ws = int(round(np.sqrt(size / r)))
        hs = int(round(ws * r))
        for s in scales:
            wss, hss = ws * s, hs * s
            anchors.append([cx - 0.5 * (wss - 1), cy - 0.5 * (hss - 1),
                            cx + 0.5 * (wss - 1), cy + 0.5 * (hss - 1)])
    return np.asarray(anchors, np.float32)


@register("_contrib_Proposal",
          aliases=("Proposal", "proposal", "_contrib_MultiProposal",
                   "MultiProposal"))
def proposal(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n=6000,
             rpn_post_nms_top_n=300, threshold=0.7, feature_stride=16,
             scales=(4, 8, 16, 32), ratios=(0.5, 1, 2), rpn_min_size=16,
             iou_loss=False, output_score=False):
    """RPN proposal op (reference: contrib/proposal.cc).  Static-shape:
    scores are top-k'd to rpn_pre_nms_top_n, greedy NMS marks suppressed
    boxes, output is padded to exactly rpn_post_nms_top_n rois per image
    — the reference pads with the first box too."""
    import numpy as np

    B, A2, H, W = cls_prob.shape
    A = len(scales) * len(ratios)
    if A2 != 2 * A:
        raise ValueError(
            f"Proposal: cls_prob has {A2} channels but scales×ratios "
            f"defines {A} anchors (need 2·{A} channels: bg+fg per anchor)")
    anchors = jnp.asarray(_make_anchors(feature_stride, scales, ratios))

    sy = jnp.arange(H, dtype=jnp.float32) * feature_stride
    sx = jnp.arange(W, dtype=jnp.float32) * feature_stride
    shift_y, shift_x = jnp.meshgrid(sy, sx, indexing="ij")
    shifts = jnp.stack([shift_x, shift_y, shift_x, shift_y],
                       axis=-1).reshape(-1, 4)          # (H*W, 4)
    all_anchors = (anchors[None] + shifts[:, None]).reshape(-1, 4)

    pre_n = min(int(rpn_pre_nms_top_n), A * H * W)
    post_n = int(rpn_post_nms_top_n)

    def one_image(scores_fg, deltas, info):
        # scores_fg: (A, H, W); deltas: (4A, H, W); info: (3,) h, w, scale
        scores = scores_fg.transpose(1, 2, 0).reshape(-1)     # (HWA,)
        d = deltas.reshape(A, 4, H, W).transpose(2, 3, 0, 1) \
            .reshape(-1, 4)
        anc = all_anchors.reshape(H * W, A, 4).reshape(-1, 4)
        # bbox transform (reference: BBoxTransformInv)
        ws = anc[:, 2] - anc[:, 0] + 1.0
        hs = anc[:, 3] - anc[:, 1] + 1.0
        cx = anc[:, 0] + 0.5 * (ws - 1)
        cy = anc[:, 1] + 0.5 * (hs - 1)
        ncx = d[:, 0] * ws + cx
        ncy = d[:, 1] * hs + cy
        nw = jnp.exp(jnp.clip(d[:, 2], -10, 10)) * ws
        nh = jnp.exp(jnp.clip(d[:, 3], -10, 10)) * hs
        boxes = jnp.stack([ncx - 0.5 * (nw - 1), ncy - 0.5 * (nh - 1),
                           ncx + 0.5 * (nw - 1), ncy + 0.5 * (nh - 1)],
                          axis=1)
        boxes = jnp.clip(boxes,
                         jnp.zeros((4,)),
                         jnp.stack([info[1] - 1, info[0] - 1,
                                    info[1] - 1, info[0] - 1]))
        # min-size filter
        min_size = rpn_min_size * info[2]
        keep = ((boxes[:, 2] - boxes[:, 0] + 1 >= min_size)
                & (boxes[:, 3] - boxes[:, 1] + 1 >= min_size))
        scores = jnp.where(keep, scores, -1.0)
        top_scores, order = lax.top_k(scores, pre_n)
        top_boxes = boxes[order]
        # greedy NMS over the sorted list
        def body(i, valid):
            cur = top_boxes[i]
            iou = _iou_corner(cur, top_boxes)
            suppress = (iou > threshold) & (jnp.arange(pre_n) > i)
            return jnp.where(suppress & valid[i], False, valid)

        valid = top_scores > -1.0
        valid = lax.fori_loop(0, pre_n, body, valid)
        # compact the survivors to the front (stable sort keeps score
        # order), truncate/pad to post_n
        sorted_idx = jnp.argsort(~valid, stable=True)
        out_boxes = top_boxes[sorted_idx][:post_n]
        out_scores = top_scores[sorted_idx][:post_n]
        n_valid = jnp.sum(valid)
        pad_mask = jnp.arange(post_n) >= n_valid
        out_boxes = jnp.where(pad_mask[:, None], out_boxes[0], out_boxes)
        out_scores = jnp.where(pad_mask, out_scores[0], out_scores)
        return out_boxes, out_scores

    fg = cls_prob[:, A:]  # foreground scores
    boxes, scores = jax.vmap(one_image)(fg, bbox_pred, im_info)
    batch_idx = jnp.repeat(jnp.arange(B, dtype=boxes.dtype), post_n)
    rois = jnp.concatenate(
        [batch_idx[:, None], boxes.reshape(-1, 4)], axis=1)
    if output_score:
        return rois, scores.reshape(-1, 1)
    return rois


def _iou_corner(box, boxes):
    tl = jnp.maximum(box[:2], boxes[:, :2])
    br = jnp.minimum(box[2:4], boxes[:, 2:4])
    wh = jnp.maximum(br - tl + 1, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    a = (box[2] - box[0] + 1) * (box[3] - box[1] + 1)
    b = (boxes[:, 2] - boxes[:, 0] + 1) * (boxes[:, 3] - boxes[:, 1] + 1)
    return inter / jnp.maximum(a + b - inter, 1e-12)
