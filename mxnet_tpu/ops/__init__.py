"""The operator library: pure JAX functions, registered by reference name.

Reference parity: src/operator/** — see the per-module docstrings.  This
namespace exposes the *pure* functions (operating on jax arrays); the
NDArray-aware generated wrappers live in ``mxnet_tpu.ndarray``.
"""

from . import registry
from .registry import register, get, list_ops, all_ops

from . import elemwise
from . import reduce as reduce_ops
from . import matrix
from . import indexing
from . import nn
from . import random_ops
from . import linalg
from . import control_flow
from . import optimizer_op
from . import ctc
from . import rnn as rnn_op
from . import attention
from . import contrib_det
from . import quantization
from . import vision_extra
from . import legacy_output
from . import moe

# Re-export every registered pure function at module level so that
# `from mxnet_tpu import ops; ops.dot(...)` works on jax arrays.  A
# submodule import may have bound a module object under an op name (the
# import system binds `ops.rnn = <module>` even under `import ... as`);
# registered op callables win over module objects.
import types as _types

for _name, _opdef in registry.all_ops().items():
    existing = globals().get(_name)
    if existing is None or isinstance(existing, _types.ModuleType):
        globals()[_name] = _opdef.fn
del _name, _opdef, existing, _types
