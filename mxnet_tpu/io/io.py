"""Data iterators producing DataBatches.

Reference parity: python/mxnet/io/io.py (DataIter, DataDesc, DataBatch,
NDArrayIter, ResizeIter, PrefetchingIter) and the C++ iterators in src/io/
(MNISTIter: iter_mnist.cc, CSVIter: iter_csv.cc, ImageRecordIter:
iter_image_recordio_2.cc, LibSVMIter).

TPU-first notes: batches are produced as host numpy and wrapped lazily —
device transfer overlaps compute through XLA async dispatch (the
reference's PrefetcherIter+copy-stream overlap).  PrefetchingIter uses a
background thread exactly like dmlc::ThreadedIter.
"""

from __future__ import annotations

import gzip
import os
import struct
import threading
from collections import namedtuple

import numpy as _np

from .. import resilience
from ..base import MXNetError
from ..ndarray.ndarray import NDArray, _from_jax


class DataDesc(namedtuple("DataDesc", ["name", "shape"])):
    """Data descriptor with dtype/layout (reference: io.DataDesc)."""

    def __new__(cls, name, shape, dtype=_np.float32, layout="NCHW"):
        ret = super().__new__(cls, name, shape)
        ret.dtype = dtype
        ret.layout = layout
        return ret

    def __repr__(self):
        return f"DataDesc[{self.name},{self.shape},{self.dtype}," \
               f"{self.layout}]"

    @staticmethod
    def get_batch_axis(layout):
        if layout is None:
            return 0
        return layout.find("N")

    @staticmethod
    def get_list(shapes, types):
        if types is not None:
            type_dict = dict(types)
            return [DataDesc(x[0], x[1], type_dict[x[0]]) for x in shapes]
        return [DataDesc(x[0], x[1]) for x in shapes]


class DataBatch:
    """One mini-batch (reference: io.DataBatch)."""

    def __init__(self, data, label=None, pad=None, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        if data is not None:
            assert isinstance(data, (list, tuple)), \
                "Data must be list of NDArrays"
        if label is not None:
            assert isinstance(label, (list, tuple)), \
                "Label must be list of NDArrays"
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label

    def __str__(self):
        data_shapes = [d.shape for d in self.data]
        if self.label:
            label_shapes = [l.shape for l in self.label]
        else:
            label_shapes = None
        return f"{self.__class__.__name__}: data shapes: {data_shapes} " \
               f"label shapes: {label_shapes}"


class DataIter:
    """Base iterator (reference: io.DataIter)."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self):
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self):
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        raise NotImplementedError


class ResizeIter(DataIter):
    """Resize another iterator to `size` batches per epoch (reference:
    io.ResizeIter)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__()
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None
        self.provide_data = data_iter.provide_data
        self.provide_label = data_iter.provide_label
        self.batch_size = data_iter.batch_size
        if hasattr(data_iter, "default_bucket_key"):
            self.default_bucket_key = data_iter.default_bucket_key

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class PrefetchingIter(DataIter):
    """Background-thread prefetch over one or more iters (reference:
    io.PrefetchingIter; C++ analog: PrefetcherIter/dmlc::ThreadedIter)."""

    def __init__(self, iters, rename_data=None, rename_label=None):
        super().__init__()
        if not isinstance(iters, list):
            iters = [iters]
        self.n_iter = len(iters)
        assert self.n_iter > 0
        self.iters = iters
        self.rename_data = rename_data
        self.rename_label = rename_label
        self.batch_size = self.provide_data[0][1][0]
        self.data_ready = [threading.Event() for _ in range(self.n_iter)]
        self.data_taken = [threading.Event() for _ in range(self.n_iter)]
        for e in self.data_taken:
            e.set()
        self.started = True
        self.current_batch = [None for _ in range(self.n_iter)]
        self.next_batch = [None for _ in range(self.n_iter)]

        def prefetch_func(self, i):
            # device placement happens HERE, on the prefetch thread, so
            # the h2d copy of batch N+1 overlaps the step on batch N
            # (MXTPU_DEVICE_PREFETCH=0 keeps batches as produced; the
            # consumer then pays the transfer synchronously)
            from ..gluon.data import prefetcher as _prefetcher

            placing = _prefetcher.default_depth() > 0
            while True:
                self.data_taken[i].wait()
                if not self.started:
                    break
                try:
                    batch = self.iters[i].next()
                    if placing:
                        batch = _prefetcher.place(batch)
                    self.next_batch[i] = batch
                except StopIteration:
                    self.next_batch[i] = None
                self.data_taken[i].clear()
                self.data_ready[i].set()

        self.prefetch_threads = [
            threading.Thread(target=prefetch_func, args=[self, i],
                             daemon=True)
            for i in range(self.n_iter)]
        for thread in self.prefetch_threads:
            thread.start()

    def __del__(self):
        self.started = False
        for e in self.data_taken:
            e.set()
        for thread in self.prefetch_threads:
            thread.join(timeout=1)

    @property
    def provide_data(self):
        if self.rename_data is None:
            return sum([i.provide_data for i in self.iters], [])
        return sum([[DataDesc(r[x.name], x.shape, x.dtype)
                     if isinstance(x, DataDesc) else DataDesc(*x)
                     for x in i.provide_data]
                    for r, i in zip(self.rename_data, self.iters)], [])

    @property
    def provide_label(self):
        if self.rename_label is None:
            return sum([i.provide_label for i in self.iters], [])
        return sum([[DataDesc(r[x.name], x.shape, x.dtype)
                     if isinstance(x, DataDesc) else DataDesc(*x)
                     for x in i.provide_label]
                    for r, i in zip(self.rename_label, self.iters)], [])

    def reset(self):
        for e in self.data_ready:
            e.wait()
        for i in self.iters:
            i.reset()
        for e in self.data_ready:
            e.clear()
        for e in self.data_taken:
            e.set()

    def iter_next(self):
        for e in self.data_ready:
            e.wait()
        if self.next_batch[0] is None:
            for i in self.next_batch:
                assert i is None, \
                    "Number of entry mismatches between iterators"
            return False
        for batch in self.next_batch:
            assert batch.pad == self.next_batch[0].pad, \
                "Number of entry mismatches between iterators"
        self.current_batch = DataBatch(
            sum([batch.data for batch in self.next_batch], []),
            sum([batch.label for batch in self.next_batch], []),
            self.next_batch[0].pad,
            self.next_batch[0].index,
            provide_data=self.provide_data,
            provide_label=self.provide_label)
        for e in self.data_ready:
            e.clear()
        for e in self.data_taken:
            e.set()
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad

    # -- resumable pipeline state ----------------------------------------------

    def _quiesce(self):
        """Wait until every prefetch thread parks (data_ready set):
        the child iterators are then untouched until data_taken."""
        for e in self.data_ready:
            e.wait()

    def state_dict(self):
        """Checkpointable position, exact at the DELIVERY point.  Each
        prefetch thread may hold one fetched-but-undelivered batch in
        ``next_batch[i]``; its child cursor is rolled back one batch in
        the recorded state, so restore re-fetches that batch instead of
        skipping it (the held batch itself is never serialized)."""
        self._quiesce()
        children = []
        for i, it in enumerate(self.iters):
            st = it.state_dict()
            if self.next_batch[i] is not None:
                st = dict(st)
                st["cursor"] = int(st["cursor"]) - self.batch_size
            children.append(st)
        return {"version": 1, "iters": children}

    def load_state_dict(self, sd):
        """Restore: the in-flight prefetched batches are DISCARDED (they
        belong to the pre-restore position) and the threads re-fetch
        from each child's restored cursor."""
        if not isinstance(sd, dict) or sd.get("version") != 1:
            raise ValueError(
                f"PrefetchingIter.load_state_dict: unsupported state "
                f"{type(sd).__name__} (want version-1 dict)")
        children = sd.get("iters")
        if not isinstance(children, list) or \
                len(children) != self.n_iter:
            raise ValueError(
                f"PrefetchingIter.load_state_dict: state has "
                f"{len(children) if isinstance(children, list) else '?'} "
                f"child iters, this prefetcher drives {self.n_iter}")
        self._quiesce()
        for it, st in zip(self.iters, children):
            it.load_state_dict(st)
        for i in range(self.n_iter):
            self.next_batch[i] = None
        for e in self.data_ready:
            e.clear()
        for e in self.data_taken:
            e.set()
        return self


def _init_data(data, allow_empty, default_name):
    """Normalize data into list of (name, numpy) (reference: io._init_data)."""
    assert data is not None or allow_empty
    if data is None:
        data = []
    if isinstance(data, (_np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, list):
        if not allow_empty:
            assert len(data) > 0
        if len(data) == 1:
            data = {default_name: data[0]}
        else:
            data = {f"_{i}_{default_name}": d for i, d in enumerate(data)}
    if not isinstance(data, dict):
        raise TypeError(
            f"Input must be NDArray, numpy.ndarray, a list of them or dict "
            f"with them as values, got {type(data)}")
    for k, v in data.items():
        if not isinstance(v, NDArray):
            try:
                data[k] = _np.asarray(v)
            except Exception:
                raise TypeError(f"Invalid type '{type(v)}' for {k}")
    return list(sorted(data.items()))


def _getdata_by_idx(data, idx):
    shuffled = []
    for k, v in data:
        if isinstance(v, NDArray):
            v = v.asnumpy()
        shuffled.append((k, v[idx]))
    return shuffled


class NDArrayIter(DataIter):
    """Iterate over in-memory arrays (reference: io.NDArrayIter) with
    pad/discard/roll_over last-batch handling and shuffling."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label"):
        super().__init__(batch_size)
        self.data = _init_data(data, allow_empty=False,
                               default_name=data_name)
        self.label = _init_data(label, allow_empty=True,
                                default_name=label_name)
        self.idx = _np.arange(self.data[0][1].shape[0])
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        self.batch_size = batch_size
        self.cursor = -self.batch_size
        self.num_data = self.idx.shape[0]
        self._cache_data = None
        self._cache_label = None
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(k, tuple([self.batch_size] + list(v.shape[1:])),
                         v.dtype)
                for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, tuple([self.batch_size] + list(v.shape[1:])),
                         v.dtype)
                for k, v in self.label]

    def hard_reset(self):
        if self.shuffle:
            self._shuffle_data()
        self.cursor = -self.batch_size
        self._cache_data = None
        self._cache_label = None

    def reset(self):
        if self.shuffle:
            self._shuffle_data()
        # roll_over: keep the tail for the next epoch
        if self.last_batch_handle == "roll_over" and \
                0 < self.cursor < self.num_data:
            self.cursor = -self.batch_size + \
                (self.cursor % self.num_data) % self.batch_size
        else:
            self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    def next(self):
        if not self.iter_next():
            raise StopIteration
        data = self.getdata()
        label = self.getlabel()
        # discard: drop ragged tail
        if data[0].shape[0] != self.batch_size and \
                self.last_batch_handle == "discard":
            raise StopIteration
        return DataBatch(data=data, label=label, pad=self.getpad(),
                         index=None)

    def _getdata(self, data_source, start=None, end=None):
        assert start is not None or end is not None
        if start is None:
            start = 0
        if end is None:
            end = data_source[0][1].shape[0] if data_source else 0
        out = []
        for _, x in data_source:
            if isinstance(x, NDArray):
                x = x.asnumpy()
            out.append(_array(x[start:end]))
        return out

    def _concat(self, first_data, second_data):
        assert len(first_data) == len(second_data)
        out = []
        for x, y in zip(first_data, second_data):
            out.append(_array(_np.concatenate(
                (x.asnumpy(), y.asnumpy()), axis=0)))
        return out

    def _batchify(self, data_source):
        assert self.cursor < self.num_data, "DataIter need reset."
        if self.last_batch_handle == "roll_over" and \
                -self.batch_size < self.cursor < 0:
            assert self._cache_data is not None or \
                self._cache_label is not None, \
                "next epoch should have cached data"
            cache = self._cache_data if self._cache_data is not None \
                else self._cache_label
            second = self._getdata(data_source,
                                   end=self.cursor + self.batch_size)
            return self._concat(cache, second)
        if self.cursor + self.batch_size <= self.num_data:
            return self._getdata(data_source, self.cursor,
                                 self.cursor + self.batch_size)
        if self.last_batch_handle == "pad":
            first = self._getdata(data_source, self.cursor)
            pad = self.batch_size - self.num_data + self.cursor
            second = self._getdata(data_source, end=pad)
            return self._concat(first, second)
        return self._getdata(data_source, self.cursor)

    def getdata(self):
        if self.last_batch_handle == "roll_over" and \
                self.cursor + self.batch_size >= self.num_data:
            # cache the tail for roll-over into next epoch
            self._cache_data = self._batchify(self.data) \
                if self._cache_data is None else self._cache_data
            return self._cache_data
        data = self._batchify(self.data)
        self._cache_data = None
        return data

    def getlabel(self):
        if self.last_batch_handle == "roll_over" and \
                self.cursor + self.batch_size >= self.num_data:
            self._cache_label = self._batchify(self.label) \
                if self._cache_label is None else self._cache_label
            return self._cache_label
        label = self._batchify(self.label)
        self._cache_label = None
        return label

    def getpad(self):
        if self.last_batch_handle == "pad" and \
                self.cursor + self.batch_size > self.num_data:
            return self.cursor + self.batch_size - self.num_data
        if self.last_batch_handle == "roll_over" and \
                -self.batch_size < self.cursor < 0:
            return -self.cursor
        return 0

    def _shuffle_data(self):
        _np.random.shuffle(self.idx)
        self.data = _getdata_by_idx(self.data, self.idx)
        self.label = _getdata_by_idx(self.label, self.idx)

    # -- resumable pipeline state ----------------------------------------------

    def state_dict(self):
        """Exact position: the epoch's permutation (``idx`` — the data
        is physically reordered by it, so it IS the epoch order) plus
        the cursor.  JSON-serializable; rides the checkpoint manifest
        via `AsyncCheckpointer.save(..., data_state=...)`."""
        return {"version": 1, "cursor": int(self.cursor),
                "idx": [int(i) for i in self.idx]}

    def load_state_dict(self, sd):
        """Adopt a recorded position with zero re-read and zero skipped
        samples.  The data is currently ordered by ``self.idx``; the
        recorded epoch order is ``sd['idx']`` — a RELATIVE permutation
        re-orders in place (``argsort(current)[wanted]``), so restore
        never needs the original un-shuffled arrays."""
        if not isinstance(sd, dict) or sd.get("version") != 1:
            raise ValueError(
                f"NDArrayIter.load_state_dict: unsupported state "
                f"{type(sd).__name__} (want version-1 dict)")
        want = _np.asarray(sd["idx"], dtype=_np.int64)
        if want.shape[0] != self.num_data or \
                not _np.array_equal(_np.sort(want),
                                    _np.arange(self.num_data)):
            raise ValueError(
                f"NDArrayIter.load_state_dict: state permutes "
                f"{want.shape[0]} samples, iterator holds "
                f"{self.num_data} (or idx is not a permutation)")
        rel = _np.argsort(self.idx)[want]
        self.data = _getdata_by_idx(self.data, rel)
        self.label = _getdata_by_idx(self.label, rel)
        self.idx = want
        self.cursor = int(sd["cursor"])
        self._cache_data = None
        self._cache_label = None
        return self


def _array(np_arr):
    import jax.numpy as jnp

    return _from_jax(jnp.asarray(np_arr))


class CSVIter(DataIter):
    """CSV reader (reference: src/io/iter_csv.cc)."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=1, round_batch=True, dtype="float32", **kwargs):
        super().__init__(batch_size)
        data = resilience.io_retry(
            lambda: _np.loadtxt(data_csv, delimiter=",", dtype=dtype),
            description=f"read {data_csv}")
        data = data.reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = resilience.io_retry(
                lambda: _np.loadtxt(label_csv, delimiter=",",
                                    dtype=dtype),
                description=f"read {label_csv}")
            label = label.reshape((-1,) + tuple(label_shape))
            if label_shape == (1,):
                label = label.reshape(-1)
        else:
            label = _np.zeros((data.shape[0],), dtype=dtype)
        self._iter = NDArrayIter(
            data, label, batch_size,
            last_batch_handle="roll_over" if round_batch else "pad")
        self.provide_data = self._iter.provide_data
        self.provide_label = self._iter.provide_label

    def reset(self):
        self._iter.reset()

    def next(self):
        return self._iter.next()


class MNISTIter(DataIter):
    """MNIST idx-format reader (reference: src/io/iter_mnist.cc)."""

    def __init__(self, image="train-images-idx3-ubyte",
                 label="train-labels-idx1-ubyte", batch_size=128,
                 shuffle=True, flat=False, silent=False, seed=0,
                 input_shape=None, **kwargs):
        super().__init__(batch_size)
        imgs = self._read_images(image)
        labels = self._read_labels(label)
        if flat:
            imgs = imgs.reshape(imgs.shape[0], -1)
        else:
            imgs = imgs.reshape(imgs.shape[0], 1, 28, 28)
        if input_shape is not None:
            imgs = imgs.reshape((imgs.shape[0],) + tuple(input_shape))
        imgs = imgs.astype(_np.float32) / 255.0
        self._iter = NDArrayIter(imgs, labels.astype(_np.float32),
                                 batch_size, shuffle=shuffle,
                                 last_batch_handle="discard")
        self.provide_data = self._iter.provide_data
        self.provide_label = self._iter.provide_label

    @staticmethod
    def _open(path):
        def opener():
            if path.endswith(".gz") or (not os.path.exists(path)
                                        and os.path.exists(path + ".gz")):
                return gzip.open(
                    path if path.endswith(".gz") else path + ".gz", "rb")
            return open(path, "rb")

        return resilience.io_retry(opener, description=f"open {path}")

    def _read_images(self, path):
        with self._open(path) as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            assert magic == 2051, f"bad MNIST image magic {magic}"
            return _np.frombuffer(f.read(n * rows * cols),
                                  dtype=_np.uint8).reshape(n, rows, cols)

    def _read_labels(self, path):
        with self._open(path) as f:
            magic, n = struct.unpack(">II", f.read(8))
            assert magic == 2049, f"bad MNIST label magic {magic}"
            return _np.frombuffer(f.read(n), dtype=_np.uint8)

    def reset(self):
        self._iter.reset()

    def next(self):
        return self._iter.next()


class ImageRecordIter(DataIter):
    """RecordIO image reader + augmentation (reference:
    src/io/iter_image_recordio_2.cc).

    Decodes JPEG/PNG payloads from a .rec file, applies the reference's
    default augmenters (resize/crop/mirror — image.py), batches, and
    prefetches on a background thread.
    """

    def __init__(self, path_imgrec, data_shape, batch_size=1,
                 path_imgidx=None, label_width=1, shuffle=False,
                 rand_crop=False, rand_mirror=False, mean_r=0.0, mean_g=0.0,
                 mean_b=0.0, std_r=1.0, std_g=1.0, std_b=1.0, scale=1.0,
                 resize=-1, round_batch=True, preprocess_threads=4,
                 prefetch_buffer=4, dtype="float32", skip_corrupt=False,
                 **kwargs):
        super().__init__(batch_size)
        from .. import recordio as rio
        from .. import image as img_mod

        self._rec = rio.MXRecordIO(path_imgrec, "r",
                                   skip_corrupt=skip_corrupt) \
            if path_imgidx is None \
            else rio.MXIndexedRecordIO(path_imgidx, path_imgrec, "r",
                                       skip_corrupt=skip_corrupt)
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.shuffle = shuffle
        self.rand_crop = rand_crop
        self.rand_mirror = rand_mirror
        self.round_batch = round_batch
        self.mean = _np.array([mean_r, mean_g, mean_b],
                              dtype=_np.float32).reshape(3, 1, 1)
        self.std = _np.array([std_r, std_g, std_b],
                             dtype=_np.float32).reshape(3, 1, 1)
        self.scale = scale
        self.resize = resize
        self.preprocess_threads = preprocess_threads
        self._img = img_mod
        # index pass: record OFFSETS only (payloads stream per batch — the
        # reference's parser also reads chunks on demand, iter_image_
        # recordio_2.cc).  Native C++ codec (src/recordio.cc) is the fast
        # path; the python codec is the fallback.
        self._native = None
        try:
            from .. import _native

            if _native.available():
                self._native = _native.NativeRecordReader(path_imgrec)
                self._offsets = self._native.scan()
        except OSError:
            self._native = None
        if self._native is None:
            self._offsets = []
            while True:
                pos = self._rec.tell()
                rec = self._rec.read()
                if rec is None:
                    break
                self._offsets.append(pos)
        self._order = _np.arange(len(self._offsets))
        self.cursor = 0
        self.reset()

    def _read_at(self, offset):
        if self._native is not None:
            return self._native.read_at(offset)
        self._rec.seek(offset)
        return self._rec.read()

    @property
    def provide_data(self):
        return [DataDesc("data",
                         (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        shape = (self.batch_size,) if self.label_width == 1 else \
            (self.batch_size, self.label_width)
        return [DataDesc("softmax_label", shape)]

    def reset(self):
        if self.shuffle:
            _np.random.shuffle(self._order)
        self.cursor = 0

    def _next_indices(self):
        n = len(self._offsets)
        if n == 0 or self.cursor >= n:
            raise StopIteration
        avail = n - self.cursor
        if avail >= self.batch_size:
            idx = list(self._order[self.cursor:self.cursor
                                   + self.batch_size])
            self.cursor += self.batch_size
            return idx
        if not self.round_batch:
            raise StopIteration  # drop ragged tail
        # round-robin: complete the last batch from the epoch's start
        idx = list(self._order[self.cursor:]) \
            + list(self._order[:self.batch_size - avail])
        self.cursor = n
        return idx

    def _decode_crop_one(self, payload):
        """Python/PIL fallback: decode + resize + crop one image -> HWC
        uint8 (mirror/normalize happen batch-vectorized afterwards)."""
        _, h, w = self.data_shape
        arr = self._img.imdecode_np(payload)  # HWC uint8
        if self.resize > 0:
            arr = self._img.resize_short_np(arr, self.resize)
        if self.rand_crop:
            return self._img.random_crop_np(arr, (w, h))
        return self._img.center_crop_np(arr, (w, h))

    def _decode_one(self, payload, mirror_flag):
        """Fully-processed single image -> normalized CHW (used for the
        sparse non-JPEG stragglers inside a native-decoded batch)."""
        arr = self._decode_crop_one(payload)
        if mirror_flag:
            arr = arr[:, ::-1, :]
        chw = arr.astype(_np.float32).transpose(2, 0, 1)
        return (chw * self.scale - self.mean) / self.std

    def next(self):
        from .. import _native
        from .. import recordio as rio

        idx = self._next_indices()
        c, h, w = self.data_shape
        data = _np.empty((self.batch_size, c, h, w), dtype=_np.float32)
        label = _np.empty((self.batch_size, self.label_width),
                          dtype=_np.float32)
        payloads = []
        for i in range(self.batch_size):
            rec = self._read_at(self._offsets[idx[i]])
            header, payload = rio.unpack(rec)
            payloads.append(payload)
            lab = header.label
            label[i] = lab if _np.ndim(lab) else [lab] * self.label_width
        # randomness drawn HERE (one RNG, seed semantics stay in python);
        # the native kernel is pure given crop seeds + mirror flags
        mirror = (_np.random.rand(self.batch_size) < 0.5) \
            if self.rand_mirror else _np.zeros(self.batch_size, bool)
        if _native.has_jpeg() and c == 3:
            # native fast path: threaded libjpeg decode + fused augment
            # (reference: iter_image_recordio_2.cc + image_aug_default.cc)
            crop_modes = _np.full(self.batch_size,
                                  -2 if self.rand_crop else -1, _np.int32)
            # draw seeds only when used: center-crop eval runs must not
            # perturb the global RNG stream vs the python fallback
            seeds = _np.random.randint(
                0, 2 ** 62, self.batch_size).astype(_np.uint64) \
                if self.rand_crop else _np.zeros(self.batch_size,
                                                 _np.uint64)
            status = _native.decode_augment_batch(
                payloads, data, resize_short=self.resize,
                crop_modes=crop_modes, seeds=seeds,
                mirror=mirror.astype(_np.uint8), scale=self.scale,
                mean=self.mean.reshape(3), std=self.std.reshape(3),
                n_threads=self.preprocess_threads)
            for i in _np.nonzero(status == 0)[0]:
                # non-JPEG payloads (e.g. PNG): python codec fallback
                data[i] = self._decode_one(payloads[i], mirror[i])
        else:
            # pure-python batch: per-sample decode/crop into one uint8
            # NHWC staging buffer, then ONE vectorized flip+normalize
            # pass straight into the float32 output (bit-identical to the
            # old per-sample float path — see normalize_flip_batch_np)
            u8 = None
            for i in range(self.batch_size):
                arr = self._decode_crop_one(payloads[i])
                if u8 is None:
                    u8 = _np.empty((self.batch_size,) + arr.shape,
                                   arr.dtype)
                u8[i] = arr
            self._img.normalize_flip_batch_np(
                u8, mirror, self.scale, self.mean, self.std, out=data)
        # cursor was already advanced by _next_indices — advancing here
        # too skipped every other batch of the epoch
        return DataBatch(
            data=[_array(data)],
            label=[_array(label[:, 0] if self.label_width == 1 else label)],
            pad=0, index=None)


class LibSVMIter(DataIter):
    """LibSVM sparse text format reader (reference: src/io/iter_libsvm.cc).

    Batches carry CSR data (the reference's behavior) so a linear model
    can run the compact ``sparse.dot`` kernels without ever
    materializing the (batch, dim) dense view; pass ``stype="default"``
    for dense batches (the pre-round-4 behavior)."""

    def __init__(self, data_libsvm, data_shape, label_shape=None,
                 batch_size=1, round_batch=True, stype="csr", **kwargs):
        super().__init__(batch_size)
        dim = data_shape[0] if isinstance(data_shape, (tuple, list)) \
            else data_shape
        self._dim = dim
        self._stype = stype
        vals, cols, indptr, labels = [], [], [0], []
        with resilience.io_retry(lambda: open(data_libsvm),
                                 description=f"open {data_libsvm}") as f:
            for line in f:
                parts = line.strip().split()
                if not parts:
                    continue
                labels.append(float(parts[0]))
                for kv in parts[1:]:
                    k, v = kv.split(":")
                    cols.append(int(k))
                    vals.append(float(v))
                indptr.append(len(vals))
        self._vals = _np.asarray(vals, _np.float32)
        self._cols = _np.asarray(cols, _np.int32)
        self._indptr = _np.asarray(indptr, _np.int64)
        self._counts = _np.diff(self._indptr)  # once, not per batch
        self._labels = _np.asarray(labels, _np.float32)
        self._n = len(labels)
        self._round = round_batch
        self._cursor = 0
        self.provide_data = [DataDesc("data", (batch_size, dim))]
        lshape = label_shape or (1,)
        if not isinstance(lshape, (tuple, list)):
            lshape = (lshape,)
        if any(s > 1 for s in lshape):
            # the parser reads exactly one label per row; advertising a
            # wider shape would lie to bind-time shape inference
            raise MXNetError(
                f"LibSVMIter: label_shape {tuple(lshape)} unsupported — "
                "label_libsvm multi-label input is not implemented; one "
                "label per row only")
        self.provide_label = [DataDesc("softmax_label", (batch_size,))]

    def reset(self):
        self._cursor = 0

    def _rows_csr(self, idx):
        """CSR slice of the given row ids as a CSRNDArray."""
        from ..ndarray.sparse import CSRNDArray

        counts = self._counts[idx]
        starts = self._indptr[idx]
        take = _np.concatenate(
            [_np.arange(s, s + c) for s, c in zip(starts, counts)]) \
            if len(idx) else _np.zeros((0,), _np.int64)
        indptr = _np.concatenate(
            [[0], _np.cumsum(counts)]).astype(_np.int32)
        return CSRNDArray(self._vals[take], self._cols[take], indptr,
                          (len(idx), self._dim))

    def next(self):
        if self._cursor >= self._n:
            raise StopIteration
        lo = self._cursor
        hi = lo + self.batch_size
        pad = 0
        if hi > self._n and not self._round:
            pad = hi - self._n
        self._cursor = hi
        idx = _np.arange(lo, hi) % self._n
        label = _array(self._labels[idx])
        if self._stype == "csr":
            data = self._rows_csr(idx)
        else:
            dense = _np.zeros((len(idx), self._dim), _np.float32)
            for r, i in enumerate(idx):
                s, e = self._indptr[i], self._indptr[i + 1]
                dense[r, self._cols[s:e]] = self._vals[s:e]
            data = _array(dense)
        return DataBatch([data], [label], pad=pad)
