"""Data iterators (reference: python/mxnet/io/ + src/io/)."""

from .io import (DataDesc, DataBatch, DataIter, ResizeIter, PrefetchingIter,
                 NDArrayIter, CSVIter, MNISTIter, ImageRecordIter,
                 LibSVMIter)
