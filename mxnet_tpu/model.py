"""Model checkpoint helpers.

Reference parity: python/mxnet/model.py — save_checkpoint/load_checkpoint
(the prefix-symbol.json + prefix-NNNN.params deploy pair) and the
BatchEndParam callback bundle.
"""

from __future__ import annotations

from collections import namedtuple

from .ndarray import load as nd_load
from .ndarray import save as nd_save

BatchEndParam = namedtuple("BatchEndParams",
                           ["epoch", "nbatch", "eval_metric", "locals"])


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params,
                    remove_amp_cast=True):
    """Reference: mx.model.save_checkpoint."""
    if symbol is not None:
        symbol.save(f"{prefix}-symbol.json")
    save_dict = {f"arg:{name}": v for name, v in arg_params.items()}
    save_dict.update({f"aux:{name}": v for name, v in aux_params.items()})
    param_name = f"{prefix}-{epoch:04d}.params"
    nd_save(param_name, save_dict)


def load_checkpoint(prefix, epoch):
    """Reference: mx.model.load_checkpoint → (symbol, arg_params,
    aux_params)."""
    from . import symbol as sym_mod

    import os

    symbol = None
    if os.path.exists(f"{prefix}-symbol.json"):
        symbol = sym_mod.load(f"{prefix}-symbol.json")
    save_dict = nd_load(f"{prefix}-{epoch:04d}.params")
    arg_params = {}
    aux_params = {}
    for k, v in save_dict.items():
        tp, _, name = k.partition(":")
        if tp == "arg":
            arg_params[name] = v
        elif tp == "aux":
            aux_params[name] = v
        else:
            arg_params[k] = v
    return symbol, arg_params, aux_params
