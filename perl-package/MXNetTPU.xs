/*
 * AI::MXNetTPU — Perl XS shim over the flat C ABI (src/mxtpu_c_api.h).
 *
 * Reference parity: perl-package/ (AI::MXNet) binds the reference
 * through c_api.h the same way; this is the identical contract over
 * libmxtpu.so.  The XS layer is deliberately thin — handles cross as
 * IVs, tensor data as packed byte strings — and everything typed lives
 * in generated Perl (lib/AI/MXNetTPU/Ops.pm).
 */
#define PERL_NO_GET_CONTEXT
#include "EXTERN.h"
#include "perl.h"
#include "XSUB.h"

#include "mxtpu_c_api.h"

MODULE = AI::MXNetTPU    PACKAGE = AI::MXNetTPU    PREFIX = xs_

PROTOTYPES: DISABLE

int
xs_init_runtime()
    CODE:
        RETVAL = MXTPUInit();
    OUTPUT:
        RETVAL

void
xs_shutdown_runtime()
    CODE:
        MXTPUShutdown();

const char *
xs_last_error()
    CODE:
        RETVAL = MXGetLastError();
    OUTPUT:
        RETVAL

IV
xs_ndarray_create(SV *databuf, AV *shape, const char *dtype)
    PREINIT:
        STRLEN len;
        const char *buf;
        int ndim, i;
        int64_t cshape[8];
        NDArrayHandle h = NULL;
    CODE:
        buf = SvPV(databuf, len);
        ndim = av_len(shape) + 1;
        if (ndim > 8)
            croak("ndarray_create: ndim %d > 8", ndim);
        for (i = 0; i < ndim; ++i)
            cshape[i] = (int64_t)SvIV(*av_fetch(shape, i, 0));
        if (MXNDArrayCreate(buf, (size_t)len, cshape, ndim, dtype, &h))
            croak("MXNDArrayCreate: %s", MXGetLastError());
        RETVAL = PTR2IV(h);
    OUTPUT:
        RETVAL

void
xs_ndarray_free(IV h)
    CODE:
        MXNDArrayFree(INT2PTR(NDArrayHandle, h));

void
xs_ndarray_shape(IV h)
    PREINIT:
        int ndim, i;
        int64_t shape[8];
    PPCODE:
        if (MXNDArrayGetShape(INT2PTR(NDArrayHandle, h), &ndim, shape))
            croak("MXNDArrayGetShape: %s", MXGetLastError());
        EXTEND(SP, ndim);
        for (i = 0; i < ndim; ++i)
            PUSHs(sv_2mortal(newSViv((IV)shape[i])));

SV *
xs_ndarray_to_bytes(IV h)
    PREINIT:
        size_t nbytes;
        NDArrayHandle nd;
        SV *out;
        char *p;
    CODE:
        nd = INT2PTR(NDArrayHandle, h);
        if (MXNDArraySize(nd, &nbytes))
            croak("MXNDArraySize: %s", MXGetLastError());
        out = newSV(nbytes ? nbytes : 1);
        SvPOK_on(out);
        p = SvPVX(out);
        if (MXNDArraySyncCopyToCPU(nd, p, nbytes))
            croak("MXNDArraySyncCopyToCPU: %s", MXGetLastError());
        SvCUR_set(out, nbytes);
        RETVAL = out;
    OUTPUT:
        RETVAL

void
xs_invoke_raw(const char *op, AV *inputs, AV *pkeys, AV *pvals)
    PREINIT:
        NDArrayHandle ins[32];
        NDArrayHandle outs[8];
        const char *keys[32];
        const char *vals[32];
        int n_in, n_params, n_out, i;
    PPCODE:
        n_in = av_len(inputs) + 1;
        n_params = av_len(pkeys) + 1;
        if (n_in > 32 || n_params > 32)
            croak("invoke: too many inputs/params");
        for (i = 0; i < n_in; ++i)
            ins[i] = INT2PTR(NDArrayHandle,
                             SvIV(*av_fetch(inputs, i, 0)));
        for (i = 0; i < n_params; ++i) {
            keys[i] = SvPV_nolen(*av_fetch(pkeys, i, 0));
            vals[i] = SvPV_nolen(*av_fetch(pvals, i, 0));
        }
        n_out = 8;
        if (MXImperativeInvoke(op, ins, n_in, keys, vals, n_params,
                               outs, &n_out))
            croak("MXImperativeInvoke(%s): %s", op, MXGetLastError());
        EXTEND(SP, n_out);
        for (i = 0; i < n_out; ++i)
            PUSHs(sv_2mortal(newSViv(PTR2IV(outs[i]))));

void
xs_list_ops_raw()
    PREINIT:
        int count, i;
        const char **names;
    PPCODE:
        if (MXListAllOpNames(&count, &names))
            croak("MXListAllOpNames: %s", MXGetLastError());
        EXTEND(SP, count);
        for (i = 0; i < count; ++i)
            PUSHs(sv_2mortal(newSVpv(names[i], 0)));

void
xs_attach_grad(IV h)
    CODE:
        if (MXAutogradAttachGrad(INT2PTR(NDArrayHandle, h)))
            croak("MXAutogradAttachGrad: %s", MXGetLastError());

void
xs_record_start()
    CODE:
        if (MXAutogradRecordStart())
            croak("MXAutogradRecordStart: %s", MXGetLastError());

void
xs_record_stop()
    CODE:
        if (MXAutogradRecordStop())
            croak("MXAutogradRecordStop: %s", MXGetLastError());

void
xs_backward(IV loss)
    CODE:
        if (MXAutogradBackward(INT2PTR(NDArrayHandle, loss)))
            croak("MXAutogradBackward: %s", MXGetLastError());

IV
xs_get_grad(IV h)
    PREINIT:
        NDArrayHandle g = NULL;
    CODE:
        if (MXNDArrayGetGrad(INT2PTR(NDArrayHandle, h), &g))
            croak("MXNDArrayGetGrad: %s", MXGetLastError());
        RETVAL = PTR2IV(g);
    OUTPUT:
        RETVAL

int
xs_kvstore_create(const char *type)
    PREINIT:
        KVStoreHandle kv;
    CODE:
        if (MXKVStoreCreate(type, &kv))
            croak("MXKVStoreCreate: %s", MXGetLastError());
        RETVAL = kv;
    OUTPUT:
        RETVAL

void
xs_kvstore_init(int kv, int key, IV v)
    CODE:
        if (MXKVStoreInit(kv, key, INT2PTR(NDArrayHandle, v)))
            croak("MXKVStoreInit: %s", MXGetLastError());

void
xs_kvstore_push(int kv, int key, IV v)
    CODE:
        if (MXKVStorePush(kv, key, INT2PTR(NDArrayHandle, v)))
            croak("MXKVStorePush: %s", MXGetLastError());

void
xs_kvstore_free(int kv)
    CODE:
        MXKVStoreFree(kv);

IV
xs_kvstore_pull(int kv, int key)
    PREINIT:
        NDArrayHandle out = NULL;
    CODE:
        if (MXKVStorePull(kv, key, &out))
            croak("MXKVStorePull: %s", MXGetLastError());
        RETVAL = PTR2IV(out);
    OUTPUT:
        RETVAL
