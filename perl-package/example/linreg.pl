#!/usr/bin/perl
# Train 1D linear regression through the Perl binding ONLY — no Python
# source in this program (reference analog: perl-package/AI-MXNet
# examples driving c_api.h; mirrors cpp-package/example/linreg.cpp).
#
# Run (after building the XS module):
#   cd perl-package && perl Makefile.PL && make
#   PYTHONPATH=$repo PERL5LIB=blib/lib:blib/arch perl example/linreg.pl
use strict;
use warnings;

use AI::MXNetTPU;
use AI::MXNetTPU::Ops;

# y = 3x - 1
my (@xs, @ys);
for my $i (0 .. 31) {
    my $x = $i / 8.0 - 2.0;
    push @xs, $x;
    push @ys, 3.0 * $x - 1.0;
}
my $x = AI::MXNetTPU::NDArray->new(\@xs, [32, 1]);
my $y = AI::MXNetTPU::NDArray->new(\@ys, [32, 1]);
my $w = AI::MXNetTPU::NDArray->new([0.0], [1, 1]);
my $b = AI::MXNetTPU::NDArray->new([0.0], [1]);
$w->attach_grad;
$b->attach_grad;

my $lr = 0.2;
for my $step (0 .. 59) {
    my $loss;
    {
        my $rec  = AI::MXNetTPU::AutogradRecord->new;
        # generated typed wrappers (Ops.pm) and the generic invoke
        # surface compose freely (varargs ops like broadcast_add keep
        # the generic spelling, as in cpp-package)
        my ($wx) = AI::MXNetTPU::Ops::dot($x, $w);
        my ($pred) = AI::MXNetTPU::invoke('broadcast_add', [$wx, $b]);
        my ($diff) = AI::MXNetTPU::invoke('broadcast_sub', [$pred, $y]);
        my ($sq)   = AI::MXNetTPU::Ops::square($diff);
        ($loss) = AI::MXNetTPU::Ops::mean($sq);
    }
    $loss->backward;
    # fused optimizer op through the same C surface
    my ($w2) = AI::MXNetTPU::invoke('sgd_update', [$w, $w->grad],
                                    { lr => $lr });
    my ($b2) = AI::MXNetTPU::invoke('sgd_update', [$b, $b->grad],
                                    { lr => $lr });
    $w = $w2;
    $b = $b2;
    $w->attach_grad;
    $b->attach_grad;
}

my $wf = $w->aslist->[0];
my $bf = $b->aslist->[0];
printf("w=%.4f b=%.4f\n", $wf, $bf);
if (abs($wf - 3.0) > 0.05 || abs($bf + 1.0) > 0.05) {
    print "FAIL\n";
    exit 1;
}
print "PASS\n";
