package AI::MXNetTPU;

# Perl frontend for the mxnet_tpu framework (reference parity:
# perl-package/AI-MXNet binding the reference through c_api.h).
# Everything below drives libmxtpu.so — no Python source in the
# caller's program; the embedded interpreter inside the library is an
# implementation detail of the C ABI (see src/c_api.cc header).

use strict;
use warnings;

our $VERSION = '0.01';

# DynaLoader with RTLD_GLOBAL (0x01), NOT XSLoader: the shim links
# libpython, and numpy/jax C extensions loaded later by the embedded
# interpreter resolve Python symbols from the GLOBAL namespace — a
# default RTLD_LOCAL dlopen leaves them unresolvable ("Error importing
# numpy..." at MXTPUInit).
use DynaLoader ();
our @ISA = ('DynaLoader');
sub dl_load_flags { 0x01 }
__PACKAGE__->bootstrap($VERSION);

my $booted = 0;

sub ensure_init {
    return if $booted;
    init_runtime() == 0 or die "MXTPUInit failed: " . last_error();
    $booted = 1;
}

# invoke a registered op: (name, \@NDArray_inputs, \%params) -> list of
# NDArrays (the generic builder; typed wrappers in AI::MXNetTPU::Ops)
sub invoke {
    my ($op, $inputs, $params) = @_;
    ensure_init();
    $params ||= {};
    my @in_h = map { $_->{handle} } @$inputs;
    my @keys = sort keys %$params;
    my @vals = map { "" . $params->{$_} } @keys;
    my @out  = invoke_raw($op, \@in_h, \@keys, \@vals);
    return map { AI::MXNetTPU::NDArray->_from_handle($_) } @out;
}

sub list_ops {
    ensure_init();
    return list_ops_raw();
}

package AI::MXNetTPU::AutogradRecord;

sub new {
    my ($class) = @_;
    AI::MXNetTPU::ensure_init();
    AI::MXNetTPU::record_start();
    return bless {}, $class;
}

sub DESTROY { AI::MXNetTPU::record_stop() }

package AI::MXNetTPU::NDArray;

# float32 NDArray over an opaque C handle.  Data crosses the boundary
# as pack("f*")-ed byte strings.

sub new {
    my ($class, $data, $shape) = @_;
    AI::MXNetTPU::ensure_init();
    my $buf = pack("f*", @$data);
    my $h = AI::MXNetTPU::ndarray_create($buf, $shape, "float32");
    return bless { handle => $h, owned => 1 }, $class;
}

sub _from_handle {
    my ($class, $h) = @_;
    return bless { handle => $h, owned => 1 }, $class;
}

sub shape { [ AI::MXNetTPU::ndarray_shape($_[0]{handle}) ] }

sub aslist {
    my ($self) = @_;
    return [ unpack("f*",
                    AI::MXNetTPU::ndarray_to_bytes($self->{handle})) ];
}

sub attach_grad { AI::MXNetTPU::attach_grad($_[0]{handle}); $_[0] }

sub grad {
    my ($self) = @_;
    return AI::MXNetTPU::NDArray->_from_handle(
        AI::MXNetTPU::get_grad($self->{handle}));
}

sub backward { AI::MXNetTPU::backward($_[0]{handle}) }

sub DESTROY {
    my ($self) = @_;
    AI::MXNetTPU::ndarray_free($self->{handle})
        if $self->{owned} && $self->{handle};
    $self->{handle} = 0;
}

package AI::MXNetTPU::KVStore;

sub new {
    my ($class, $type) = @_;
    AI::MXNetTPU::ensure_init();
    return bless { kv => AI::MXNetTPU::kvstore_create($type || "local") },
        $class;
}

sub init { AI::MXNetTPU::kvstore_init($_[0]{kv}, $_[1], $_[2]{handle}) }
sub push_ { AI::MXNetTPU::kvstore_push($_[0]{kv}, $_[1], $_[2]{handle}) }

sub pull {
    my ($self, $key) = @_;
    return AI::MXNetTPU::NDArray->_from_handle(
        AI::MXNetTPU::kvstore_pull($self->{kv}, $key));
}

sub DESTROY {
    my ($self) = @_;
    AI::MXNetTPU::kvstore_free($self->{kv}) if defined $self->{kv};
    delete $self->{kv};
}

1;
__END__

=head1 NAME

AI::MXNetTPU - Perl binding for the mxnet_tpu framework over its C ABI

=head1 SYNOPSIS

  use AI::MXNetTPU;
  use AI::MXNetTPU::Ops;   # generated typed op wrappers

  my $x = AI::MXNetTPU::NDArray->new([1, 2, 3], [3]);
  my ($y) = AI::MXNetTPU::Ops::sin_($x);   # perl builtins get a _ suffix
  print "@{$y->aslist}\n";

=cut
