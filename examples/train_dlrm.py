#!/usr/bin/env python
"""DLRM-style recommender on the captured sparse path (reference:
example/recommenders — the click-through tier).

Three categorical fields live as columns of one dense batch tensor;
each gets a row-sparse `ShardedEmbedding` (``feature=<col>`` selects
its id column), the continuous tail goes through a bottom MLP, and the
concatenated factors feed a top MLP for click logits.  The whole step
— gather, loss, segment-sum scatter-add row update — runs as ONE
donated program per unique-count bucket (gluon/captured.py), and the
`DevicePrefetcher` dedupes the NEXT batch's ids on its producer
thread while the current step computes (``sparse_tables=net``).
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import embedding, gluon
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon.data.prefetcher import DevicePrefetcher


class DLRM(gluon.HybridBlock):
    """Embeddings + bottom MLP -> concat -> top MLP -> click logit."""

    def __init__(self, n_users, n_items, n_cats, dim, n_dense, **kw):
        super().__init__(**kw)
        self._n_dense = n_dense
        with self.name_scope():
            self.emb_user = embedding.ShardedEmbedding(n_users, dim,
                                                       feature=0)
            self.emb_item = embedding.ShardedEmbedding(n_items, dim,
                                                       feature=1)
            self.emb_cat = embedding.ShardedEmbedding(n_cats, dim,
                                                      feature=2)
            self.bottom = nn.Dense(dim, activation="relu",
                                   in_units=n_dense, flatten=False)
            self.top = nn.HybridSequential()
            with self.top.name_scope():
                self.top.add(nn.Dense(16, activation="relu",
                                      in_units=4 * dim, flatten=False),
                             nn.Dense(1, in_units=16, flatten=False))

    def hybrid_forward(self, F, x):
        # x: (batch, 3 + n_dense) — id columns first, continuous tail
        dense = self.bottom(x[:, 3:])
        z = F.concat(self.emb_user(x), self.emb_item(x),
                     self.emb_cat(x), dense, dim=-1)
        return self.top(z).squeeze(-1)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--users", type=int, default=500)
    parser.add_argument("--items", type=int, default=400)
    parser.add_argument("--cats", type=int, default=64)
    parser.add_argument("--dim", type=int, default=8)
    parser.add_argument("--dense", type=int, default=4)
    parser.add_argument("--steps", type=int, default=120)
    parser.add_argument("--batch-size", type=int, default=128)
    args = parser.parse_args()

    mx.random.seed(0)
    rng = np.random.RandomState(0)

    # synthetic clicks: an affinity planted in the id arithmetic so the
    # tables have something to learn
    w_u = rng.randn(args.users).astype(np.float32)
    w_i = rng.randn(args.items).astype(np.float32)

    def make_batch():
        u = rng.randint(0, args.users, args.batch_size)
        i = rng.randint(0, args.items, args.batch_size)
        c = rng.randint(0, args.cats, args.batch_size)
        d = rng.randn(args.batch_size, args.dense).astype(np.float32)
        logit = w_u[u] + w_i[i] + 0.5 * d[:, 0]
        y = (logit > 0).astype(np.float32)
        x = np.concatenate(
            [np.stack([u, i, c], axis=1).astype(np.float32), d], axis=1)
        return mx.nd.array(x), mx.nd.array(y)

    batches = [make_batch() for _ in range(args.steps)]

    net = DLRM(args.users, args.items, args.cats, args.dim, args.dense)
    net.initialize(init=mx.init.Xavier())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.01})
    loss_fn = gluon.loss.SigmoidBinaryCrossEntropyLoss()

    from mxnet_tpu.gluon import captured
    captured.reset_counters()
    # the prefetcher's producer thread dedupes the NEXT batch's ids per
    # table and stashes them for the captured step (embedding/prep.py)
    prefetcher = DevicePrefetcher(batches, sparse_tables=net)
    first = last = None
    step = 0
    for x, y in prefetcher:
        loss = trainer.train_step(net, loss_fn, x, y)
        v = float(loss.asnumpy().mean())
        first = v if first is None else first
        last = v
        if step % 40 == 0:
            print(f"step {step}: loss {v:.4f}")
        step += 1
    prefetcher.close()

    dispatches = captured.dispatch_count()
    print(f"{step} steps, {dispatches} captured dispatches, "
          f"{captured.trace_count()} traces")
    print(f"loss first {first:.4f} -> last {last:.4f}")
    ok = last < 0.9 * first and dispatches == step
    print("dlrm OK" if ok else "dlrm FAILED "
          f"(loss {first:.4f}->{last:.4f}, dispatches "
          f"{dispatches}/{step})")
    if not ok:
        sys.exit(1)


if __name__ == "__main__":
    main()
