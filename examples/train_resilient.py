#!/usr/bin/env python
"""Resilient training: crash-resume with run_resilient (docs/resilience.md).

Trains a gluon MLP under ``resilience.run_resilient`` and — unless
``--no-fault`` — injects a SIGTERM preemption mid-run through the
``MXTPU_FAULT_INJECT`` harness.  The driver checkpoints inside the grace
window, restarts in-process, resumes from the checkpoint, and finishes
every step; the final report shows the recovery.  Delete nothing and run
again with the same ``--ckpt-dir`` to watch it resume across processes.

``--nan-step N`` demonstrates the numerical half of the story instead
(docs/resilience.md "Numerical resilience"): step N's gradients are
poisoned with NaN through the ``nan_grad`` fault site, the fused guard
skips the step with weights untouched (``trainer.skipped_steps``), a
``numerics.DivergenceMonitor`` watches the loss EWMA, and the run still
converges.
"""

import argparse
import os
import shutil
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, numerics, resilience
from mxnet_tpu.gluon import nn


def build(batch_size, seed=7, nan_step=None):
    mx.random.seed(seed)
    rng = np.random.RandomState(seed)
    centers = rng.uniform(-2, 2, (4, 16)).astype(np.float32)
    y = rng.randint(0, 4, 1024)
    x = centers[y] + rng.normal(0, 0.5, (1024, 16)).astype(np.float32)
    batches = [(mx.nd.array(x[i:i + batch_size]),
                mx.nd.array(y[i:i + batch_size].astype(np.float32)))
               for i in range(0, 1024, batch_size)]

    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(32, activation="relu"), nn.Dense(4))
    net.initialize(init=mx.init.Xavier())
    # plain SGD: the optimizer is stateless, so params ARE the state
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    params = net.collect_params()

    def step_fn(step):
        if nan_step is not None and step == nan_step:
            # arm the nan_grad site so THIS step's gradients are poisoned
            os.environ["MXTPU_FAULT_INJECT"] = "nan_grad:1"
            resilience.reset_faults()
        data, label = batches[step % len(batches)]
        with autograd.record():
            loss = loss_fn(net(data), label)
        loss.backward()
        trainer.step(data.shape[0])
        return float(loss.asnumpy().mean())

    def get_state():
        return {k: p.data().asnumpy() for k, p in params.items()}

    def set_state(state):
        for k, v in state.items():
            params[k].set_data(mx.nd.array(v))

    return step_fn, get_state, set_state, trainer


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=60)
    parser.add_argument("--batch-size", type=int, default=64)
    parser.add_argument("--checkpoint-every", type=int, default=10)
    parser.add_argument("--ckpt-dir", default=None,
                        help="checkpoint directory (default: a temp dir "
                             "removed on success)")
    parser.add_argument("--crash-step", type=int, default=25,
                        help="inject a SIGTERM preemption at this step")
    parser.add_argument("--no-fault", action="store_true",
                        help="run without the injected preemption")
    parser.add_argument("--nan-step", type=int, default=None,
                        help="poison this step's gradients with NaN "
                             "instead of the SIGTERM demo (numerical-"
                             "health guard)")
    parser.add_argument("--sync-ckpt", action="store_true",
                        help="synchronous saves (default: the native "
                             "async snapshot-and-commit engine)")
    args = parser.parse_args()

    ckpt_dir = args.ckpt_dir or tempfile.mkdtemp(prefix="resilient_ckpt_")
    inject_sigterm = args.no_fault is False and args.nan_step is None
    if inject_sigterm and "MXTPU_FAULT_INJECT" not in os.environ:
        os.environ["MXTPU_FAULT_INJECT"] = \
            f"sigterm_at_step:{args.crash_step}"
        resilience.reset_faults()
        print(f"injecting preemption: "
              f"MXTPU_FAULT_INJECT={os.environ['MXTPU_FAULT_INJECT']}")

    step_fn, get_state, set_state, trainer = build(
        args.batch_size, nan_step=args.nan_step)
    # make_checkpointer picks the engine: the native async snapshot-and-
    # commit engine by default (crash-atomic two-phase commit, saves off
    # the training thread); --sync-ckpt forces synchronous saves
    ck = mx.checkpoint.make_checkpointer(
        ckpt_dir, max_to_keep=3,
        async_save=False if args.sync_ckpt else None)
    if args.nan_step is not None:
        # divergence watchdog: rolls back to the last snapshot if the
        # run ever goes unhealthy for MXTPU_MAX_BAD_STEPS in a row
        trainer.divergence_monitor = numerics.DivergenceMonitor(
            checkpointer=ck, set_state=set_state)
    report = resilience.run_resilient(
        step_fn, ck, args.steps, get_state=get_state,
        set_state=set_state, checkpoint_every=args.checkpoint_every,
        max_restarts=3)

    first = report.losses.get(min(report.losses, default=0), float("nan"))
    last = report.losses.get(max(report.losses, default=0), float("nan"))
    print(f"{report}")
    print(f"loss {first:.4f} -> {last:.4f} over {report.final_step} steps")
    assert report.final_step == args.steps
    if inject_sigterm:
        assert report.preempted and report.restarts >= 1
        print(f"preempted at step {args.crash_step}, checkpointed, "
              f"resumed from step {report.resumed_from[-1]}: "
              f"recovery OK")
    if args.nan_step is not None:
        assert trainer.skipped_steps, \
            "the poisoned step was not skipped (is MXTPU_GRAD_GUARD off?)"
        print(f"NaN gradient at step {args.nan_step} -> "
              f"{trainer.skipped_steps[-1]}: weights untouched, run "
              f"converged anyway")
    assert last < first, "loss did not decrease"
    if args.ckpt_dir is None:
        shutil.rmtree(ckpt_dir, ignore_errors=True)
    print("train_resilient: all checks passed")


if __name__ == "__main__":
    main()
