#!/usr/bin/env python
"""Deployment pipeline: train → export (symbol.json + .params) →
re-import with SymbolBlock → int8 post-training quantization → ONNX.

Reference analogs: example/image-classification's save/load flow,
example/quantization/imagenet_gen_qsym.py, and the contrib.onnx export
tutorial — composed into the one deployment story.

Run:  python examples/deploy_export_quantize.py [--out-dir /tmp/deploy]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.contrib import onnx as onnx_mxnet
from mxnet_tpu.contrib import quantization as qz


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out-dir", default="/tmp/mxtpu_deploy")
    ap.add_argument("--steps", type=int, default=30)
    args = ap.parse_args()
    os.makedirs(args.out_dir, exist_ok=True)

    rs = np.random.RandomState(0)

    # 1. a small convnet, trained briefly on synthetic data
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(16, 3, padding=1),
            gluon.nn.BatchNorm(),
            gluon.nn.Activation("relu"),
            gluon.nn.MaxPool2D(2),
            gluon.nn.Flatten(),
            gluon.nn.Dense(10))
    net.initialize(init=mx.init.Xavier())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 1e-3})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    x = mx.nd.array(rs.randn(32, 3, 16, 16).astype("float32"))
    y = mx.nd.array(rs.randint(0, 10, 32).astype("float32"))
    for i in range(args.steps):
        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        trainer.step(32)
    print(f"trained {args.steps} steps, loss "
          f"{float(loss.mean().asnumpy()):.4f}")

    # 2. export the deploy format (reference: HybridBlock.export)
    prefix = os.path.join(args.out_dir, "model")
    net.export(prefix)
    print(f"exported {prefix}-symbol.json + {prefix}-0000.params")

    # 3. reload WITHOUT the python class (reference: SymbolBlock.imports)
    deployed = gluon.SymbolBlock.imports(
        f"{prefix}-symbol.json", ["data"], f"{prefix}-0000.params")
    with autograd.predict_mode():
        ref = net(x)
    drift = float(abs(deployed(x).asnumpy() - ref.asnumpy()).max())
    print(f"SymbolBlock reload drift: {drift:.2e}")

    # 4. int8 post-training quantization with entropy calibration
    calib = [mx.nd.array(rs.randn(32, 3, 16, 16).astype("float32"))
             for _ in range(4)]
    qnet = qz.quantize_net(net, calib_data=calib, calib_mode="naive")
    qdrift = float(abs(qnet(x).asnumpy() - ref.asnumpy()).max())
    print(f"int8 max drift: {qdrift:.3f} "
          f"(scale {float(abs(ref.asnumpy()).max()):.3f})")

    # 5. ONNX for everything else (reference: contrib.onnx export_model)
    sym = mx.sym.trace_block(net)
    params = {n: p.data() for n, p in net.collect_params().items()}
    onnx_path = onnx_mxnet.export_model(
        sym, params, [(32, 3, 16, 16)],
        onnx_file_path=os.path.join(args.out_dir, "model.onnx"))
    back = onnx_mxnet.import_to_gluon(onnx_path)
    odrift = float(abs(back(x).asnumpy() - ref.asnumpy()).max())
    print(f"ONNX round-trip drift: {odrift:.2e}")
    assert drift < 1e-4 and odrift < 1e-4
    print("deploy pipeline OK")


if __name__ == "__main__":
    main()
