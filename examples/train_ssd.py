#!/usr/bin/env python
"""SSD detection training (reference: example/ssd/train.py over the
MultiBox ops — the detection-training tier of the example zoo).

Trains the compact SSD from the model zoo on synthetic box data (a
bright rectangle on a dark field; class = rectangle orientation), with
the whole forward+MultiBoxTarget+loss recorded as one tape node so the
step jit-compiles with static shapes — the reference's dynamic-shape
risk (SURVEY §7) resolved by the padded-label convention (cls=-1 pads).

Point --rec at an im2rec detection pack to train on real data via
ImageDetIter instead.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon.model_zoo import SSD, SSDTrainLoss, ssd_detect


def synthetic_batch(rng, batch_size, size, max_boxes=2):
    """Images with 1-2 axis-aligned bright rectangles; label (B, M, 5)
    rows are [cls, xmin, ymin, xmax, ymax] in [0,1], cls=-1 padding.
    Class 0: wide rectangle, class 1: tall rectangle."""
    x = rng.uniform(0, 0.1, (batch_size, 3, size, size)).astype(np.float32)
    lab = -np.ones((batch_size, max_boxes, 5), np.float32)
    for b in range(batch_size):
        for m in range(rng.randint(1, max_boxes + 1)):
            cls = rng.randint(0, 2)
            w, h = (0.45, 0.25) if cls == 0 else (0.25, 0.45)
            cx = rng.uniform(w / 2, 1 - w / 2)
            cy = rng.uniform(h / 2, 1 - h / 2)
            box = [cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2]
            px = [int(round(v * size)) for v in box]
            x[b, :, px[1]:px[3], px[0]:px[2]] = rng.uniform(0.8, 1.0)
            lab[b, m] = [cls] + box
    return mx.nd.array(x), mx.nd.array(lab)


def get_batches(args):
    if args.rec:
        if not os.path.exists(args.rec):
            sys.exit(f"--rec {args.rec}: no such file")
        it = mx.image.ImageDetIter(
            batch_size=args.batch_size, data_shape=(3, args.size, args.size),
            path_imgrec=args.rec, shuffle=True)
        for step, batch in enumerate(it):
            if step >= args.steps:
                break
            yield batch.data[0], batch.label[0]
        return
    rng = np.random.RandomState(0)
    for _ in range(args.steps):
        yield synthetic_batch(rng, args.batch_size, args.size)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--rec", default="", help="im2rec detection pack")
    parser.add_argument("--batch-size", type=int, default=8)
    parser.add_argument("--size", type=int, default=64)
    parser.add_argument("--steps", type=int, default=30)
    parser.add_argument("--lr", type=float, default=0.05)
    args = parser.parse_args()

    net = SSD(num_classes=2)
    net.initialize(init=mx.init.Xavier())
    net.hybridize()
    loss_fn = SSDTrainLoss(negative_mining_ratio=3)

    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr, "momentum": 0.9})

    first = last = None
    for step, (x, lab) in enumerate(get_batches(args)):
        with autograd.record():
            loss = loss_fn(net(x), lab)
        loss.backward()
        trainer.step(x.shape[0])
        val = float(loss.asnumpy())
        first = val if first is None else first
        last = val
        if step % 10 == 0:
            print(f"step {step}: loss {val:.4f}")
    if first is None:
        sys.exit("no batches produced (rec pack smaller than one batch?)")
    print(f"loss first {first:.4f} -> last {last:.4f}")

    # inference decode on a fresh batch (reference: example/ssd/demo.py)
    x, lab = synthetic_batch(np.random.RandomState(7), 2, args.size)
    det = ssd_detect(net, x, score_threshold=0.1)
    kept = int((det.asnumpy()[:, :, 0] >= 0).sum())
    print(f"detect: {kept} boxes above threshold, output {det.shape}")
    print("ssd training OK" if last < first else "ssd loss did not drop")


if __name__ == "__main__":
    main()
