#!/usr/bin/env python
"""Causal-LM (GPT) pretraining over a dp×tp(×sp) mesh — the decoder-only
counterpart of bert_pretrain_sharded.py, on the same primitives: one
jitted sharded step (ShardedTrainer), scanned causal trunk, and any
``--attention`` impl (flash's Pallas kernel, ring/ulysses sequence
parallelism for long context).
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import parallel
from mxnet_tpu.gluon.model_zoo import gpt


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--model", default="gpt2_small",
                        choices=["gpt_tiny", "gpt2_small",
                                 "gpt2_medium"])
    parser.add_argument("--batch-size", type=int, default=8)
    parser.add_argument("--seq-len", type=int, default=128)
    parser.add_argument("--steps", type=int, default=30)
    parser.add_argument("--lr", type=float, default=3e-4)
    parser.add_argument("--dp", type=int, default=0, help="0 = auto")
    parser.add_argument("--tp", type=int, default=1)
    parser.add_argument("--sp", type=int, default=1)
    parser.add_argument("--attention", default="dense",
                        choices=["dense", "flash", "ring", "ulysses"])
    parser.add_argument("--dtype", default="float32")
    args = parser.parse_args()

    import jax

    n = len(jax.devices())
    dp = args.dp or max(1, n // (args.tp * args.sp))
    mesh = parallel.make_mesh(dp=dp, tp=args.tp, sp=args.sp)
    parallel.set_default_mesh(mesh)
    print(f"mesh: dp={dp} tp={args.tp} sp={args.sp} "
          f"({n} devices), attention={args.attention}")

    vocab = 1024 if args.model == "gpt_tiny" else 50257
    net = getattr(gpt, args.model)(
        vocab_size=vocab, max_length=args.seq_len,
        attention_impl=args.attention, scan_layers=True, dropout=0.0)
    net.initialize(init=mx.init.Xavier())
    if args.dtype != "float32":
        net.cast(args.dtype)

    rules = parallel.TRANSFORMER_TP_RULES if args.tp > 1 else None
    trainer = parallel.ShardedTrainer(
        net, gpt.GPTLMLoss(), "adamw", {"learning_rate": args.lr},
        mesh=mesh, rules=rules)

    rng = np.random.RandomState(0)
    # synthetic corpus with learnable structure: tok_{t+1} = f(tok_t)
    perm = rng.permutation(vocab)
    ids0 = rng.randint(0, vocab, (args.batch_size,))
    seqs = [ids0]
    for _ in range(args.seq_len - 1):
        seqs.append(perm[seqs[-1]])
    ids = np.stack(seqs, axis=1).astype(np.int32)

    first = last = None
    t0 = time.perf_counter()
    for step in range(args.steps):
        loss = trainer.step(mx.nd.array(ids), mx.nd.array(ids))
        val = float(np.asarray(loss._data, dtype=np.float32))
        first = first if first is not None else val
        last = val
        if step % 10 == 0:
            print(f"step {step}: nll {val:.3f}")
    dt = time.perf_counter() - t0
    tput = args.batch_size * args.seq_len * args.steps / dt
    print(f"nll {first:.3f} -> {last:.3f}; "
          f"{tput:.0f} tokens/sec ({args.steps} steps)")
    assert last < first, "loss did not decrease"
    print("GPT sharded pretrain OK")


if __name__ == "__main__":
    main()
