#!/usr/bin/env python
"""Mixture-of-Experts training with expert parallelism over a mesh.

No reference analog (MXNet has no MoE) — this demonstrates the
Switch/GShard-style sparse FFN (gluon.contrib.MoEFFN) sharded dp×ep via
ShardedTrainer + MOE_EP_RULES: each ep slice holds a contiguous block of
experts, GSPMD derives the dispatch/combine collectives.

Run on the virtual CPU mesh:
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/moe_expert_parallel.py --dp 2 --ep 4
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import gluon, parallel
from mxnet_tpu.gluon.contrib import MoEFFN


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dp", type=int, default=2)
    ap.add_argument("--ep", type=int, default=4)
    ap.add_argument("--units", type=int, default=32)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--k", type=int, default=2)
    args = ap.parse_args()

    mesh = parallel.make_mesh(dp=args.dp, ep=args.ep)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(args.units, activation="relu"),
            MoEFFN(units=args.units, hidden=args.hidden,
                   num_experts=args.ep * 2, k=args.k,
                   capacity_factor=2.0),
            gluon.nn.Dense(1))
    net.initialize(init=mx.init.Xavier())

    trainer = parallel.ShardedTrainer(
        net, gluon.loss.L2Loss(), "adam", {"learning_rate": 1e-2},
        mesh=mesh, rules=parallel.MOE_EP_RULES)

    rs = np.random.RandomState(0)
    batch = 8 * args.dp
    x = rs.randn(batch, 16).astype("float32")
    y = np.sin(x.sum(axis=1, keepdims=True)).astype("float32")

    for step in range(args.steps):
        loss = trainer.step(mx.nd.array(x), mx.nd.array(y))
        if step % max(1, args.steps // 5) == 0 or step == args.steps - 1:
            print(f"step {step}: loss "
                  f"{float(np.asarray(loss._data, dtype=np.float32)):.5f}")
    print(f"MoE dp={args.dp}×ep={args.ep} training OK "
          f"({args.ep * 2} experts, top-{args.k})")


if __name__ == "__main__":
    main()
