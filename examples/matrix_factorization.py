#!/usr/bin/env python
"""Matrix-factorization recommender (reference: example/recommenders/
+ example/sparse/matrix_factorization — the embedding-heavy tier).

Rating(u, i) ≈ <U_u, V_i> + b_u + c_i on synthetic low-rank ratings.
The embeddings use ``sparse_grad=True``: each step's gradient is a
compact row_sparse NDArray over the rows the batch touched (the
round-4 sparse path — 245× smaller than dense at 1M rows), and the
optimizer updates exactly those rows."""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn


class MFBlock(gluon.HybridBlock):
    def __init__(self, n_users, n_items, dim, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.user_embed = nn.Embedding(n_users, dim,
                                           sparse_grad=True)
            self.item_embed = nn.Embedding(n_items, dim,
                                           sparse_grad=True)
            self.user_bias = nn.Embedding(n_users, 1, sparse_grad=True)
            self.item_bias = nn.Embedding(n_items, 1, sparse_grad=True)

    def hybrid_forward(self, F, users, items):
        p = self.user_embed(users) * self.item_embed(items)
        return (F.sum(p, axis=-1) + self.user_bias(users).squeeze(-1)
                + self.item_bias(items).squeeze(-1))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--users", type=int, default=400)
    parser.add_argument("--items", type=int, default=300)
    parser.add_argument("--dim", type=int, default=8)
    parser.add_argument("--steps", type=int, default=200)
    parser.add_argument("--batch-size", type=int, default=256)
    args = parser.parse_args()

    mx.random.seed(0)
    np.random.seed(0)
    rng = np.random.RandomState(0)

    # ground-truth low-rank ratings
    U = rng.randn(args.users, args.dim).astype(np.float32) * 0.5
    V = rng.randn(args.items, args.dim).astype(np.float32) * 0.5
    net = MFBlock(args.users, args.items, args.dim)
    net.initialize(init=mx.init.Normal(0.05))
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.02})
    l2 = gluon.loss.L2Loss()

    first = last = None
    checked_sparse = False
    for step in range(args.steps):
        u = rng.randint(0, args.users, args.batch_size)
        i = rng.randint(0, args.items, args.batch_size)
        r = (U[u] * V[i]).sum(1) + rng.normal(0, 0.05, args.batch_size) \
            .astype(np.float32)
        with autograd.record():
            loss = l2(net(mx.nd.array(u), mx.nd.array(i)), mx.nd.array(r))
        loss.backward()
        if not checked_sparse:
            g = net.user_embed.weight.grad()
            stype = getattr(g, "stype", "default")
            n_rows = g.indices.shape[0] if stype == "row_sparse" else -1
            print(f"user-embed grad stype={stype}, "
                  f"{n_rows}/{args.users} rows touched")
            assert stype == "row_sparse"
            checked_sparse = True
        trainer.step(args.batch_size)
        v = float(loss.mean().asnumpy())
        first = v if first is None else first
        last = v
        if step % 50 == 0:
            print(f"step {step}: loss {v:.4f}")

    rmse = np.sqrt(2 * last)  # L2Loss is 0.5*(p-r)^2
    print(f"loss first {first:.4f} -> last {last:.4f} (RMSE {rmse:.3f})")
    print("matrix factorization OK" if last < 0.25 * first
          else "matrix factorization did not converge")
    if last >= 0.25 * first:
        sys.exit(1)


if __name__ == "__main__":
    main()
