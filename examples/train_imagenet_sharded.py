#!/usr/bin/env python
"""ResNet-50 data-parallel training over the device mesh (reference:
example/image-classification/train_imagenet.py — the BASELINE ResNet-50
config; kvstore='device' replaced by the compiled mesh step).

Reads ImageNet-style .rec files when given; otherwise synthetic batches.
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import gluon, parallel
from mxnet_tpu.gluon.model_zoo import vision


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--rec", default=None, help=".rec training file")
    parser.add_argument("--batch-size", type=int, default=256,
                        help="GLOBAL batch size over the mesh")
    parser.add_argument("--image-size", type=int, default=224)
    parser.add_argument("--network", default="resnet50_v1")
    parser.add_argument("--dtype", default="bfloat16")
    parser.add_argument("--steps", type=int, default=100)
    parser.add_argument("--lr", type=float, default=0.1)
    args = parser.parse_args()

    import jax

    n = len(jax.devices())
    mesh = parallel.data_parallel_mesh(n)
    print(f"devices: {n}, mesh: {mesh}")

    net = vision.get_model(args.network, classes=1000)
    net.initialize(init=mx.init.Xavier())
    if args.dtype != "float32":
        net.cast(args.dtype)
    trainer = parallel.ShardedTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": args.lr, "momentum": 0.9, "wd": 1e-4,
         "lr_scheduler": mx.lr_scheduler.CosineScheduler(
             max_update=args.steps, base_lr=args.lr, warmup_steps=5)},
        mesh=mesh)

    if args.rec:
        it = mx.io.ImageRecordIter(
            path_imgrec=args.rec, batch_size=args.batch_size,
            data_shape=(3, args.image_size, args.image_size),
            shuffle=True, rand_mirror=True, rand_crop=True)

        def batches():
            while True:
                it.reset()
                for b in it:
                    yield b.data[0], b.label[0]
    else:
        print("no --rec given; synthetic data")
        rng = np.random.RandomState(0)
        import jax.numpy as jnp

        x = jnp.asarray(rng.standard_normal(
            (args.batch_size, 3, args.image_size, args.image_size)),
            dtype=args.dtype)
        y = jnp.asarray(rng.randint(0, 1000, args.batch_size)
                        .astype(np.float32))

        def batches():
            while True:
                yield x, y

    # prefetch + place batches with the data-parallel sharding up front:
    # trainer.step's device_put then finds them already distributed and
    # the h2d copy of batch N+1 overlaps the step on batch N
    gen = iter(gluon.data.DevicePrefetcher(batches(), mesh=mesh))
    t0 = None
    for step in range(args.steps):
        x, y = next(gen)
        loss = trainer.step(x, y)
        if step == 1:
            loss.wait_to_read()
            t0 = time.perf_counter()
        if step % 20 == 0:
            print(f"step {step} loss {float(loss.asscalar()):.4f} "
                  f"lr {trainer.learning_rate:.4f}")
    loss.wait_to_read()
    dt = time.perf_counter() - t0
    sps = args.batch_size * (args.steps - 2) / dt
    print(f"throughput: {sps:.1f} samples/sec "
          f"({sps / n:.1f} samples/sec/chip)")


if __name__ == "__main__":
    main()
