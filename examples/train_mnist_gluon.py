#!/usr/bin/env python
"""MNIST training with the Gluon API (reference:
example/gluon/mnist/mnist.py — the BASELINE 'MLP on MNIST' config).

Uses real MNIST idx files when present under --data-dir; otherwise a
synthetic stand-in so the example always runs.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.callback import BatchEndParam, Speedometer
from mxnet_tpu.gluon import nn


def get_data(data_dir, batch_size):
    img = os.path.join(data_dir, "train-images-idx3-ubyte")
    if os.path.exists(img) or os.path.exists(img + ".gz"):
        train = mx.io.MNISTIter(
            image=img,
            label=os.path.join(data_dir, "train-labels-idx1-ubyte"),
            batch_size=batch_size, flat=True)
        val = mx.io.MNISTIter(
            image=os.path.join(data_dir, "t10k-images-idx3-ubyte"),
            label=os.path.join(data_dir, "t10k-labels-idx1-ubyte"),
            batch_size=batch_size, flat=True, shuffle=False)
        return train, val
    print("MNIST files not found; using synthetic data")
    rng = np.random.RandomState(0)
    centers = rng.uniform(-1, 1, (10, 784)).astype(np.float32)
    y = rng.randint(0, 10, 4096)
    x = centers[y] + rng.normal(0, 0.3, (4096, 784)).astype(np.float32)
    train = mx.io.NDArrayIter(x, y.astype(np.float32), batch_size,
                              shuffle=True)
    val = mx.io.NDArrayIter(x[:512], y[:512].astype(np.float32),
                            batch_size)
    return train, val


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--data-dir", default="data/mnist")
    parser.add_argument("--batch-size", type=int, default=128)
    parser.add_argument("--epochs", type=int, default=5)
    parser.add_argument("--lr", type=float, default=0.1)
    parser.add_argument("--hybridize", action="store_true", default=True)
    args = parser.parse_args()

    train_iter, val_iter = get_data(args.data_dir, args.batch_size)
    # keep MXTPU_DEVICE_PREFETCH batches in flight on device so the h2d
    # copy of the next batch overlaps the current step
    train_iter = gluon.data.DevicePrefetcher(train_iter)
    val_iter = gluon.data.DevicePrefetcher(val_iter)

    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(256, activation="relu"),
                nn.Dense(128, activation="relu"),
                nn.Dense(10))
    net.initialize(init=mx.init.Xavier())
    if args.hybridize:
        net.hybridize()

    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr, "momentum": 0.9})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    speedometer = Speedometer(args.batch_size, frequent=50)

    for epoch in range(args.epochs):
        train_iter.reset()
        train_metric = mx.metric.Accuracy()
        for nbatch, batch in enumerate(train_iter):
            x, y = batch.data[0], batch.label[0]
            with autograd.record():
                out = net(x)
                loss = loss_fn(out, y)
            loss.backward()
            trainer.step(args.batch_size)
            train_metric.update([y], [out])
            speedometer(BatchEndParam(epoch, nbatch, train_metric))
        val_iter.reset()
        val_metric = mx.metric.Accuracy()
        for batch in val_iter:
            val_metric.update([batch.label[0]], [net(batch.data[0])])
        print(f"epoch {epoch}: train-acc "
              f"{train_metric.get()[1]:.4f}  val-acc "
              f"{val_metric.get()[1]:.4f}")


if __name__ == "__main__":
    main()
