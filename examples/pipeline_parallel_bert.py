#!/usr/bin/env python
"""Pipeline-parallel BERT pretraining over the `pp` mesh axis
(reference: example/model-parallel* — the model-partitioning tier; the
reference partitions with `group2ctx`, here the trunk is a real GPipe /
1F1B pipeline compiled as ONE XLA program over a Mesh).

The model = token-embedding prologue + N homogeneous encoder stages
(one per pp device) + MLM-head epilogue
(gluon.model_zoo.bert.bert_pipeline_parts).  On CPU this runs on the
virtual 8-device mesh (see tests/conftest.py); on a pod slice the same
script shards over real chips.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import parallel
from mxnet_tpu.gluon.model_zoo import bert


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--pp", type=int, default=4,
                        help="pipeline stages (= devices on the pp axis)")
    parser.add_argument("--layers-per-stage", type=int, default=1)
    parser.add_argument("--schedule", choices=("gpipe", "1f1b"),
                        default="1f1b")
    parser.add_argument("--n-micro", type=int, default=8)
    parser.add_argument("--batch-size", type=int, default=16)
    parser.add_argument("--seq-len", type=int, default=32)
    parser.add_argument("--units", type=int, default=32)
    parser.add_argument("--vocab", type=int, default=256)
    parser.add_argument("--steps", type=int, default=8)
    args = parser.parse_args()

    mx.random.seed(0)
    np.random.seed(0)

    mesh = parallel.make_mesh(pp=args.pp)
    embed, layers, head = bert.bert_pipeline_parts(
        vocab_size=args.vocab, units=args.units,
        num_layers=args.pp * args.layers_per_stage,
        num_heads=max(2, args.units // 16), max_length=args.seq_len,
        dropout=0.0)
    for b in [embed] + layers + [head]:
        b.initialize(init=mx.init.Xavier())

    pt = parallel.PipelineTrainer(
        layers, bert.BERTMLMLoss(), "adamw", {"learning_rate": 3e-3},
        mesh=mesh, n_microbatches=args.n_micro, prologue=embed,
        epilogue=head, schedule=args.schedule)

    rng = np.random.RandomState(0)
    first = last = None
    for step in range(args.steps):
        ids = rng.randint(0, args.vocab,
                          (args.batch_size, args.seq_len)).astype(np.int32)
        mlm = np.where(rng.rand(*ids.shape) < 0.3, ids,
                       -1).astype(np.float32)
        loss = float(pt.step(mx.nd.array(ids),
                             mx.nd.array(mlm)).asscalar())
        first = loss if first is None else first
        last = loss
        if step % 2 == 0:
            print(f"step {step}: loss {loss:.4f}")

    print(f"schedule={args.schedule} stages={args.pp} "
          f"micro={args.n_micro} bubble={pt.bubble_fraction:.3f} "
          f"({pt.schedule_ticks} ticks)")
    print(f"loss first {first:.4f} -> last {last:.4f}")
    print("pipeline pretrain OK" if last < first
          else "pipeline loss did not drop")
    if last >= first:
        sys.exit(1)


if __name__ == "__main__":
    main()
