#!/usr/bin/env python
"""Faster R-CNN end-to-end training (reference: example/rcnn/train_end2end.py
— the two-stage detection tier of the example zoo).

The full reference pipeline, condensed to a CI-runnable synthetic task:

  backbone -> RPN head -> (anchor targets: numpy, like rpn/rpn.py)
           -> Proposal op (static-shape RPN decode + NMS, autograd-paused)
           -> proposal_target (numpy, like the reference's CustomOp
              rcnn/io/rpn.py proposal_target layer)
           -> ROIAlign -> R-CNN head -> cls + per-class bbox refinement

All on-device shapes are static (padded ROI/label tensors, cls=-1/weight=0
padding) so every op jit-compiles once — the reference's dynamic-shape
proposal path resolved by the padded contract Proposal already provides.

Synthetic data matches train_ssd.py: bright axis-aligned rectangles,
class = orientation (0 wide, 1 tall).
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn

NUM_CLASSES = 2          # foreground classes; rcnn head sees K+1 with bg=0
STRIDE = 8
SCALES = (3,)
RATIOS = (0.5, 1, 2)
A = len(SCALES) * len(RATIOS)
POST_NMS = 16            # rois per image out of Proposal
GT_PAD = 2               # max gt boxes per image (synthetic)
ROIS_PER_IMG = POST_NMS + GT_PAD   # gt boxes appended like the reference


# the SAME anchor seed the Proposal op decodes with — the numpy RPN
# targets and the op's grid must agree bit-exactly, so share the formula
from mxnet_tpu.ops.vision_extra import _make_anchors as make_anchors


def grid_anchors(fh, fw):
    anchors = make_anchors(STRIDE, SCALES, RATIOS)          # (A, 4)
    sy = np.arange(fh, dtype=np.float32) * STRIDE
    sx = np.arange(fw, dtype=np.float32) * STRIDE
    shift = np.stack(np.meshgrid(sx, sy, indexing="xy"), 0)  # (2,fh,fw) x,y
    shifts = np.stack([shift[0], shift[1], shift[0], shift[1]],
                      -1).reshape(-1, 4)                    # (fh*fw, 4)
    return (anchors[None] + shifts[:, None]).reshape(-1, 4)  # (fh*fw*A, 4)


def iou_matrix(a, b):
    """(N,4) x (M,4) corner-format IoU in pixel coords."""
    tl = np.maximum(a[:, None, :2], b[None, :, :2])
    br = np.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = np.clip(br - tl + 1, 0, None)
    inter = wh[..., 0] * wh[..., 1]
    ar = (a[:, 2] - a[:, 0] + 1) * (a[:, 3] - a[:, 1] + 1)
    br_ = (b[:, 2] - b[:, 0] + 1) * (b[:, 3] - b[:, 1] + 1)
    return inter / np.maximum(ar[:, None] + br_[None] - inter, 1e-12)


def bbox_transform(rois, gt):
    """Box -> regression target (dx,dy,dw,dh), reference bbox_transform."""
    w = rois[:, 2] - rois[:, 0] + 1
    h = rois[:, 3] - rois[:, 1] + 1
    cx = rois[:, 0] + 0.5 * (w - 1)
    cy = rois[:, 1] + 0.5 * (h - 1)
    gw = gt[:, 2] - gt[:, 0] + 1
    gh = gt[:, 3] - gt[:, 1] + 1
    gcx = gt[:, 0] + 0.5 * (gw - 1)
    gcy = gt[:, 1] + 0.5 * (gh - 1)
    return np.stack([(gcx - cx) / w, (gcy - cy) / h,
                     np.log(gw / w), np.log(gh / h)], 1).astype(np.float32)


def anchor_targets(anchors, gt_px, gt_cls):
    """RPN targets for one image (reference AnchorTargetLayer): label
    1/0/-1(ignore), bbox target + weight per anchor."""
    N = anchors.shape[0]
    label = -np.ones(N, np.float32)
    btarget = np.zeros((N, 4), np.float32)
    bweight = np.zeros((N, 1), np.float32)
    valid = gt_cls >= 0
    if valid.any():
        gt = gt_px[valid]
        iou = iou_matrix(anchors, gt)               # (N, G)
        max_iou = iou.max(1)
        argmax = iou.argmax(1)
        label[max_iou < 0.3] = 0
        label[max_iou >= 0.5] = 1
        label[iou.argmax(0)] = 1                    # best anchor per gt
        pos = label == 1
        btarget[pos] = bbox_transform(anchors[pos], gt[argmax[pos]])
        bweight[pos] = 1.0
    else:
        label[:] = 0
    return label, btarget, bweight


def proposal_targets(rois_px, gt_px, gt_cls):
    """R-CNN targets for one image's padded roi set (reference
    proposal_target CustomOp): class label (0=bg), per-class bbox target
    + weight."""
    R = rois_px.shape[0]
    cls = np.zeros(R, np.float32)
    btarget = np.zeros((R, NUM_CLASSES + 1, 4), np.float32)
    bweight = np.zeros((R, NUM_CLASSES + 1, 4), np.float32)
    valid = gt_cls >= 0
    if valid.any():
        gt = gt_px[valid]
        iou = iou_matrix(rois_px, gt)
        max_iou = iou.max(1)
        argmax = iou.argmax(1)
        fg = max_iou >= 0.5
        cls[fg] = gt_cls[valid][argmax[fg]] + 1     # 0 is background
        t = bbox_transform(rois_px[fg], gt[argmax[fg]])
        for i, r in zip(np.where(fg)[0], t):
            k = int(cls[i])
            btarget[i, k] = r
            bweight[i, k] = 1.0
    return cls, btarget, bweight


class RCNN(gluon.HybridBlock):
    """Tiny Faster R-CNN: conv backbone (stride 8), RPN head, fc R-CNN
    head (reference: rcnn/symbol/symbol_resnet.py, scaled down)."""

    def __init__(self, channels=32, **kw):
        super().__init__(**kw)
        with self.name_scope():
            self.backbone = nn.HybridSequential()
            for i, c in enumerate((channels // 2, channels, channels)):
                self.backbone.add(nn.Conv2D(c, 3, strides=2, padding=1))
                self.backbone.add(nn.Activation("relu"))
            self.rpn_conv = nn.Conv2D(channels, 3, padding=1,
                                      activation="relu")
            self.rpn_cls = nn.Conv2D(2 * A, 1)
            self.rpn_bbox = nn.Conv2D(4 * A, 1)
            self.fc = nn.HybridSequential()
            self.fc.add(nn.Dense(128, activation="relu"))
            self.rcnn_cls = nn.Dense(NUM_CLASSES + 1)
            self.rcnn_bbox = nn.Dense(4 * (NUM_CLASSES + 1))

    def features(self, x):
        feat = self.backbone(x)
        rpn = self.rpn_conv(feat)
        return feat, self.rpn_cls(rpn), self.rpn_bbox(rpn)

    def heads(self, pooled):
        h = self.fc(pooled.reshape((pooled.shape[0], -1)))
        return self.rcnn_cls(h), self.rcnn_bbox(h)


def synthetic_batch(rng, batch_size, size):
    x = rng.uniform(0, 0.1, (batch_size, 3, size, size)).astype(np.float32)
    lab = -np.ones((batch_size, GT_PAD, 5), np.float32)
    for b in range(batch_size):
        for m in range(rng.randint(1, GT_PAD + 1)):
            cls = rng.randint(0, NUM_CLASSES)
            w, h = (0.45, 0.25) if cls == 0 else (0.25, 0.45)
            cx = rng.uniform(w / 2, 1 - w / 2)
            cy = rng.uniform(h / 2, 1 - h / 2)
            box = [cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2]
            px = [int(round(v * size)) for v in box]
            x[b, :, px[1]:px[3], px[0]:px[2]] = rng.uniform(0.8, 1.0)
            lab[b, m] = [cls] + box
    return x, lab


def smooth_l1_sum(pred, target, weight, norm):
    """sum(smooth_l1(w*(p-t))) / norm — the reference's rpn/rcnn bbox
    loss normalization (sum over coords / number of positives)."""
    l = mx.nd.smooth_l1((pred - target) * weight, scalar=1.0)
    return l.sum() / norm


def train_step(net, trainer, ce, x_np, lab_np, size, anchors):
    B = x_np.shape[0]
    x = mx.nd.array(x_np)
    im_info = mx.nd.array(np.tile([size, size, 1.0], (B, 1)))

    # numpy-side RPN targets (net-independent)
    rpn_lab, rpn_bt, rpn_bw = zip(*[
        anchor_targets(anchors, lab_np[b, :, 1:] * size, lab_np[b, :, 0])
        for b in range(B)])
    rpn_lab = mx.nd.array(np.stack(rpn_lab))            # (B, N)
    rpn_bt = mx.nd.array(np.stack(rpn_bt))              # (B, N, 4)
    rpn_bw = mx.nd.array(np.stack(rpn_bw))              # (B, N, 1)

    with autograd.record():
        feat, cls_logit, bbox_pred = net.features(x)
        fh, fw = cls_logit.shape[2], cls_logit.shape[3]
        # (B, 2A, h, w) -> (B, h*w*A, 2) matching the anchor grid order
        cls_hw = cls_logit.reshape((B, 2, A, fh, fw)) \
            .transpose((0, 3, 4, 2, 1)).reshape((B, -1, 2))
        bbox_hw = bbox_pred.reshape((B, A, 4, fh, fw)) \
            .transpose((0, 3, 4, 1, 2)).reshape((B, -1, 4))
        rpn_cls_loss = ce(cls_hw, rpn_lab,
                          (rpn_lab >= 0).expand_dims(2)).mean()
        rpn_bbox_loss = smooth_l1_sum(
            bbox_hw, rpn_bt, rpn_bw, mx.nd.maximum(rpn_bw.sum(), 1.0))

        with autograd.pause():
            cls_prob = mx.nd.softmax(
                cls_logit.reshape((B, 2, A, fh, fw)), axis=1) \
                .reshape((B, 2 * A, fh, fw))
            rois = mx.nd.contrib.Proposal(
                cls_prob, bbox_pred, im_info,
                rpn_pre_nms_top_n=64, rpn_post_nms_top_n=POST_NMS,
                threshold=0.7, feature_stride=STRIDE, scales=SCALES,
                ratios=RATIOS, rpn_min_size=4)      # (B*POST_NMS, 5)
            rois_np = rois.asnumpy().reshape(B, POST_NMS, 5)
            # append gt boxes so fg rois exist from step 0 (reference
            # proposal_target does exactly this)
            gt_rois = np.concatenate(
                [np.arange(B, dtype=np.float32)[:, None, None].repeat(
                    GT_PAD, 1),
                 np.clip(lab_np[:, :, 1:], 0, 1) * size], axis=2)
            all_rois = np.concatenate([rois_np, gt_rois], axis=1)
            tgt = [proposal_targets(all_rois[b, :, 1:],
                                    lab_np[b, :, 1:] * size,
                                    lab_np[b, :, 0]) for b in range(B)]
            rcnn_lab = mx.nd.array(np.concatenate([t[0] for t in tgt]))
            rcnn_bt = mx.nd.array(np.concatenate([t[1] for t in tgt]))
            rcnn_bw = mx.nd.array(np.concatenate([t[2] for t in tgt]))
            roi_nd = mx.nd.array(all_rois.reshape(-1, 5))

        pooled = mx.nd.contrib.ROIAlign(
            feat, roi_nd, pooled_size=(4, 4), spatial_scale=1.0 / STRIDE)
        rcnn_cls, rcnn_reg = net.heads(pooled)
        rcnn_reg = rcnn_reg.reshape((-1, NUM_CLASSES + 1, 4))
        rcnn_cls_loss = ce(rcnn_cls, rcnn_lab).mean()
        rcnn_bbox_loss = smooth_l1_sum(
            rcnn_reg, rcnn_bt, rcnn_bw,
            mx.nd.maximum(rcnn_bw.sum() / 4.0, 1.0))
        loss = (rpn_cls_loss + rpn_bbox_loss + rcnn_cls_loss
                + rcnn_bbox_loss)
    loss.backward()
    trainer.step(B)
    return [float(v.asnumpy()) for v in
            (loss, rpn_cls_loss, rcnn_cls_loss)]


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--batch-size", type=int, default=4)
    parser.add_argument("--size", type=int, default=64)
    parser.add_argument("--steps", type=int, default=20)
    parser.add_argument("--lr", type=float, default=0.02)
    args = parser.parse_args()
    if args.size % STRIDE or args.size < 4 * STRIDE:
        sys.exit(f"--size must be a multiple of {STRIDE} (>= {4 * STRIDE}): "
                 f"the anchor grid is built at stride {STRIDE}")

    mx.random.seed(0)
    np.random.seed(0)
    net = RCNN()
    net.initialize(init=mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr, "momentum": 0.9})
    ce = gluon.loss.SoftmaxCrossEntropyLoss()

    fh = fw = args.size // STRIDE
    anchors = grid_anchors(fh, fw)

    rng = np.random.RandomState(0)
    first = last = None
    for step in range(args.steps):
        x_np, lab_np = synthetic_batch(rng, args.batch_size, args.size)
        total, rpn_c, rcnn_c = train_step(
            net, trainer, ce, x_np, lab_np, args.size, anchors)
        first = total if first is None else first
        last = total
        if step % 5 == 0:
            print(f"step {step}: loss {total:.4f} "
                  f"(rpn_cls {rpn_c:.4f} rcnn_cls {rcnn_c:.4f})")
    print(f"loss first {first:.4f} -> last {last:.4f}")
    print("rcnn training OK" if last < first else "rcnn loss did not drop")


if __name__ == "__main__":
    main()
