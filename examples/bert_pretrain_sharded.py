#!/usr/bin/env python
"""BERT pretraining over a dp×tp mesh with LAMB (the BASELINE
'BERT-base + hybridize→XLA + LAMB' config; reference model lives in
GluonNLP — here it's native, gluon/model_zoo/bert.py).

Long sequences: pass --attention ring and a mesh with an sp axis to run
ring attention (sequence parallelism) inside the same compiled step.
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import parallel
from mxnet_tpu.gluon.model_zoo import bert


def synthetic_batch(rng, batch, seq_len, vocab):
    ids = rng.randint(0, vocab, (batch, seq_len)).astype(np.int32)
    mlm = np.where(rng.rand(batch, seq_len) < 0.15, ids, -1) \
        .astype(np.float32)
    nsp = rng.randint(0, 2, (batch,)).astype(np.float32)
    return ids, (mx.nd.array(mlm), mx.nd.array(nsp))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--model", default="bert_base",
                        choices=["bert_tiny", "bert_base", "bert_large"])
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--seq-len", type=int, default=128)
    parser.add_argument("--steps", type=int, default=50)
    parser.add_argument("--lr", type=float, default=1e-3)
    parser.add_argument("--dp", type=int, default=0, help="0 = auto")
    parser.add_argument("--tp", type=int, default=1)
    parser.add_argument("--sp", type=int, default=1)
    parser.add_argument("--attention", default="dense",
                        choices=["dense", "flash", "ring", "ulysses"])
    parser.add_argument("--dtype", default="bfloat16")
    args = parser.parse_args()

    import jax

    n = len(jax.devices())
    dp = args.dp or max(1, n // (args.tp * args.sp))
    mesh = parallel.make_mesh(dp=dp, tp=args.tp, sp=args.sp)
    parallel.set_default_mesh(mesh)
    print(f"mesh: dp={dp} tp={args.tp} sp={args.sp}")

    builder = getattr(bert, args.model)
    net = builder(max_length=args.seq_len,
                  attention_impl=args.attention)
    net.initialize(init=mx.init.Xavier())
    if args.dtype != "float32":
        net.cast(args.dtype)
    vocab = net.word_embed_weight.shape[0]

    trainer = parallel.ShardedTrainer(
        net, bert.BERTPretrainLoss(), "lamb",
        {"learning_rate": args.lr,
         "lr_scheduler": mx.lr_scheduler.PolyScheduler(
             max_update=args.steps, base_lr=args.lr, warmup_steps=5)},
        mesh=mesh, rules=parallel.TRANSFORMER_TP_RULES)

    rng = np.random.RandomState(0)
    ids, labels = synthetic_batch(rng, args.batch_size, args.seq_len,
                                  vocab)
    trainer.step(ids, labels).wait_to_read()  # compile
    t0 = time.perf_counter()
    for step in range(args.steps):
        loss = trainer.step(ids, labels)
        if step % 10 == 0:
            print(f"step {step} loss {float(loss.asscalar()):.4f}")
    loss.wait_to_read()
    dt = time.perf_counter() - t0
    toks = args.batch_size * args.seq_len * args.steps / dt
    print(f"throughput: {toks:.0f} tokens/sec "
          f"({toks / n:.0f} tokens/sec/chip)")


if __name__ == "__main__":
    main()
