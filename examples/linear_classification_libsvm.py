"""Sparse linear classification on LibSVM data (reference:
example/sparse/linear_classification/train.py).

The end-to-end CSR path (VERDICT r3 task #5): LibSVMIter yields CSR
batches; the logistic-regression forward is ``nd.sparse.dot(csr, W)`` —
the compact gather/segment-sum kernel, O(nnz·D) compute with no dense
(batch, dim) view — and the backward is the compact transpose kernel
(``dot(csrᵀ, dy)``), so a high-dimensional sparse dataset trains
without ever materializing dense feature matrices.

Run: python examples/linear_classification_libsvm.py [--dim 10000]
"""

import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, io, nd


def make_libsvm(path, n_rows, dim, nnz_per_row, rs):
    """Synthetic separable-ish problem: y = sign(w_true · x)."""
    w_true = rs.standard_normal(dim).astype(np.float32)
    with open(path, "w") as f:
        for _ in range(n_rows):
            cols = np.sort(rs.choice(dim, size=nnz_per_row,
                                     replace=False))
            vals = rs.standard_normal(nnz_per_row).astype(np.float32)
            y = 1.0 if float(vals @ w_true[cols]) > 0 else 0.0
            feats = " ".join(f"{c}:{v:.4f}" for c, v in zip(cols, vals))
            f.write(f"{y:.0f} {feats}\n")


def main(dim=10000, n_rows=512, batch_size=64, epochs=10, lr=1.0,
         seed=0):
    rs = np.random.RandomState(seed)
    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "train.libsvm")
        make_libsvm(path, n_rows, dim, nnz_per_row=16, rs=rs)

        train = io.LibSVMIter(data_libsvm=path, data_shape=(dim,),
                              batch_size=batch_size)
        w = nd.zeros((dim, 1))
        b = nd.zeros((1,))
        w.attach_grad()
        b.attach_grad()

        acc = 0.0
        for epoch in range(epochs):
            train.reset()
            correct = total = 0
            for batch in train:
                x_csr, y = batch.data[0], batch.label[0]
                yv = y.asnumpy().reshape(-1, 1)
                with autograd.record():
                    # compact kernel: no dense (batch, dim) view
                    logits = nd.sparse.dot(x_csr, w) + b
                    loss = nd.mean(
                        nd.relu(logits) - logits * nd.array(yv) +
                        nd.log(1 + nd.exp(-nd.abs(logits))))
                loss.backward()
                for p in (w, b):
                    p._set_data(p._data - lr * p.grad._data)
                    p.grad._set_data(p.grad._data * 0)
                pred = (logits.asnumpy() > 0).astype(np.float32)
                correct += int((pred == yv).sum())
                total += len(yv)
            acc = correct / total
            print(f"epoch {epoch}: train accuracy {acc:.3f}")
        assert acc > 0.9, f"sparse linear model failed to fit ({acc})"
        print(f"final accuracy {acc:.3f} (dim={dim}, "
              f"nnz/row=16 — dense view never built)")
        return acc


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--dim", type=int, default=10000)
    ap.add_argument("--epochs", type=int, default=5)
    args = ap.parse_args()
    main(dim=args.dim, epochs=args.epochs)
