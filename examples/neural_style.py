#!/usr/bin/env python
"""Neural style transfer (reference: example/neural-style — Gatys et
al. 2015, the classic optimize-the-image example).

A small randomly-initialized VGG-style conv stack provides the feature
maps (the reference downloads VGG-19 weights; zero-egress here — random
features still define valid content/Gram-style objectives, which is all
the optimization loop needs).  The IMAGE is the parameter: autograd
drives pixels to match content features + style Gram matrices.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd
from mxnet_tpu.gluon import nn


def feature_net():
    """Conv tower exposing per-block activations."""
    blocks = []
    net = nn.HybridSequential()
    for i, ch in enumerate((16, 32, 64)):
        blk = nn.HybridSequential(prefix=f"b{i}_")
        with blk.name_scope():
            blk.add(nn.Conv2D(ch, 3, padding=1, activation="relu"))
            if i:
                blk.add(nn.AvgPool2D(2))
        net.add(blk)
        blocks.append(blk)
    return net, blocks


def extract(blocks, x):
    feats = []
    h = x
    for blk in blocks:
        h = blk(h)
        feats.append(h)
    return feats


def gram(f):
    B, C = f.shape[0], f.shape[1]
    flat = f.reshape((B, C, -1))
    return mx.nd.batch_dot(flat, flat.transpose((0, 2, 1))) \
        / (f.shape[2] * f.shape[3])


def synthetic_images(size):
    """Content: centered bright square.  Style: diagonal stripes."""
    yy, xx = np.mgrid[0:size, 0:size]
    content = np.zeros((1, 3, size, size), np.float32)
    content[:, :, size // 4:3 * size // 4, size // 4:3 * size // 4] = 0.8
    style = np.tile(((yy + xx) % 8 < 4).astype(np.float32),
                    (1, 3, 1, 1)) * 0.9
    return content, style


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--size", type=int, default=32)
    parser.add_argument("--steps", type=int, default=60)
    parser.add_argument("--lr", type=float, default=0.05)
    parser.add_argument("--style-weight", type=float, default=50.0)
    args = parser.parse_args()

    mx.random.seed(0)
    np.random.seed(0)
    net, blocks = feature_net()
    net.initialize(init=mx.init.Xavier())

    c_np, s_np = synthetic_images(args.size)
    content, style = mx.nd.array(c_np), mx.nd.array(s_np)
    with autograd.predict_mode():
        c_feats = extract(blocks, content)
        s_grams = [gram(f) for f in extract(blocks, style)]

    img = mx.nd.array(np.random.uniform(0.3, 0.7,
                                        c_np.shape).astype(np.float32))
    img.attach_grad()
    # the IMAGE is the parameter: Adam through the updater API
    # (reference uses mx.optimizer the same way; adaptive scaling
    # matters — raw feature gradients are tiny)
    updater = mx.optimizer.get_updater(
        mx.optimizer.create("adam", learning_rate=args.lr))

    first = last = None
    for step in range(args.steps):
        with autograd.record():
            feats = extract(blocks, img)
            # content: deepest block; style: gram of every block
            c_loss = ((feats[-1] - c_feats[-1]) ** 2).mean()
            s_loss = sum(((gram(f) - g) ** 2).mean()
                         for f, g in zip(feats, s_grams))
            loss = c_loss + args.style_weight * s_loss
        loss.backward()
        updater(0, img.grad, img)
        img._set_data(img.clip(0, 1)._data)
        img.grad[:] = 0
        v = float(loss.asnumpy())
        first = v if first is None else first
        last = v
        if step % 20 == 0:
            print(f"step {step}: loss {v:.5f} "
                  f"(content {float(c_loss.asnumpy()):.5f})")

    print(f"loss first {first:.5f} -> last {last:.5f}")
    print("neural style OK" if last < 0.5 * first
          else "neural style did not converge")
    if last >= 0.5 * first:
        sys.exit(1)


if __name__ == "__main__":
    main()
