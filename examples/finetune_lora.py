#!/usr/bin/env python
"""LoRA fine-tuning of a pretrained classifier head (beyond-reference
example: the modern fine-tuning tier over gluon.contrib.lora).

Stage 1 "pretrains" a small MLP classifier on a base synthetic task;
stage 2 freezes it and adapts ONLY low-rank adapters (and measures how
few parameters that is) to a shifted task the frozen model misclassifies.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon.contrib import apply_lora


def make_task(rng, n, dim, classes, rotate=False):
    """Gaussian blobs; `rotate` applies a full random orthogonal mix of
    the feature space (the domain shift — same labels, rotated view)."""
    centers = rng.uniform(-2, 2, (classes, dim)).astype(np.float32)
    y = rng.randint(0, classes, n)
    x = centers[y] + rng.normal(0, 0.5, (n, dim)).astype(np.float32)
    if rotate:
        q, _ = np.linalg.qr(
            np.random.RandomState(42).randn(dim, dim))
        x = (x @ q.astype(np.float32))
    return x, y.astype(np.float32)


def train(net, x, y, steps, lr, batch):
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": lr})
    lf = gluon.loss.SoftmaxCrossEntropyLoss()
    n = x.shape[0]
    for step in range(steps):
        i = (step * batch) % (n - batch + 1)
        xb = mx.nd.array(x[i:i + batch])
        yb = mx.nd.array(y[i:i + batch])
        with autograd.record():
            loss = lf(net(xb), yb)
        loss.backward()
        tr.step(batch)
    return float(loss.mean().asnumpy())


def accuracy(net, x, y):
    pred = net(mx.nd.array(x)).asnumpy().argmax(1)
    return float((pred == y).mean())


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--dim", type=int, default=16)
    parser.add_argument("--classes", type=int, default=4)
    parser.add_argument("--steps", type=int, default=150)
    parser.add_argument("--rank", type=int, default=4)
    args = parser.parse_args()

    mx.random.seed(0)
    np.random.seed(0)
    rng = np.random.RandomState(0)

    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(64, activation="relu"),
            gluon.nn.Dense(64, activation="relu"),
            gluon.nn.Dense(args.classes))
    net.initialize(init=mx.init.Xavier())

    # stage 1: pretrain on the base task
    xb, yb = make_task(rng, 1024, args.dim, args.classes)
    train(net, xb, yb, args.steps, 2e-3, 64)
    base_acc = accuracy(net, xb, yb)
    print(f"pretrain accuracy {base_acc:.3f}")

    # the shifted task breaks the frozen model
    xs, ys = make_task(np.random.RandomState(0), 1024, args.dim,
                       args.classes, rotate=True)
    shifted_before = accuracy(net, xs, ys)

    # stage 2: adapt ONLY low-rank adapters
    wrapped = apply_lora(net, rank=args.rank, alpha=2 * args.rank,
                         patterns=("dense",))
    n_total = sum(int(np.prod(p.shape))
                  for p in net.collect_params().values())
    n_train = sum(int(np.prod(p.shape))
                  for p in net.collect_params().values()
                  if p.grad_req != "null")
    print(f"adapters: {len(wrapped)} layers, trainable {n_train}"
          f"/{n_total} params ({100.0 * n_train / n_total:.1f}%)")
    train(net, xs, ys, args.steps, 5e-3, 64)
    shifted_after = accuracy(net, xs, ys)
    print(f"shifted-task accuracy {shifted_before:.3f} -> "
          f"{shifted_after:.3f}")

    for blk in wrapped:
        blk.merge()
    merged_acc = accuracy(net, xs, ys)
    print(f"after merge(): {merged_acc:.3f}")
    ok = (shifted_after > shifted_before + 0.1
          and abs(merged_acc - shifted_after) < 0.02
          and n_train < 0.2 * n_total)
    print("lora finetune OK" if ok else "lora finetune FAILED")
    if not ok:
        sys.exit(1)


if __name__ == "__main__":
    main()
