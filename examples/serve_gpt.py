"""Train → checkpoint → serve → hot-reload, end to end in miniature.

The serving-tier story (mxnet_tpu/serving/, docs/serving.md) on one
box: pretrain a character GPT for a few steps, commit its weights with
AsyncCheckpointer, stand up a ReplicaServer (AOT bucketed programs +
continuous batcher + checkpoint poller), serve concurrent requests,
then keep training and commit a newer checkpoint — the replica
hot-swaps the new weights between batches, without dropping a request
and without a single retrace.

Run: python examples/serve_gpt.py [--steps 30] [--requests 8]
"""

import argparse
import codecs
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, checkpoint, gluon, nd, serving
from mxnet_tpu.gluon.model_zoo import gpt


def corpus():
    import contextlib
    import io

    with contextlib.redirect_stdout(io.StringIO()):
        import this as this_mod

    return codecs.decode(this_mod.s, "rot13")


def train(net, loss_fn, trainer, data, steps, rs, seq_len=16, batch=16):
    last = None
    for _ in range(steps):
        starts = rs.randint(0, len(data) - seq_len - 1, batch)
        ids = nd.array(np.stack([data[s:s + seq_len] for s in starts])
                       .astype(np.float32))
        with autograd.record():
            loss = loss_fn(net(ids), ids)
        loss.backward()
        trainer.step(batch)
        last = float(loss.asnumpy())
    return last


def main(steps=30, requests=8, new_tokens=8, seed=0):
    mx.random.seed(seed)
    rs = np.random.RandomState(seed)
    text = corpus()
    vocab = sorted(set(text))
    stoi = {c: i for i, c in enumerate(vocab)}
    itos = dict(enumerate(vocab))
    data = np.array([stoi[c] for c in text], np.int32)

    # scan_layers=True: the scanned trunk is both the fast training
    # layout and the serving checkpoint convention (docs/serving.md)
    net = gpt.gpt_tiny(vocab_size=len(vocab), max_length=16,
                       scan_layers=True)
    net.initialize(init=mx.init.Xavier())
    loss_fn = gpt.GPTLMLoss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 3e-3})

    loss0 = train(net, loss_fn, trainer, data, steps, rs)
    print(f"trained {steps} steps, loss {loss0:.3f}")

    ckdir = tempfile.mkdtemp(prefix="serve_gpt_")
    ck = checkpoint.AsyncCheckpointer(ckdir, rank=0, world_size=1)
    ck.save(1, serving.state_for_serving(net))
    ck.wait()

    engine = serving.ServingEngine(net, batch_buckets=(1, 2, 4))
    t0 = time.perf_counter()
    engine.warmup()
    print(f"warmup: {engine.program_count()} AOT programs in "
          f"{(time.perf_counter() - t0) * 1e3:.0f} ms")
    traces = serving.trace_count()

    replica = serving.ReplicaServer(engine, ckpt_dir=ckdir, poll_ms=25,
                                    max_delay_ms=2)

    def serve_round(tag):
        prompts = [[stoi[c] for c in "the "],
                   [stoi[c] for c in "beauti"],
                   [stoi[c] for c in "simple "],
                   [stoi[c] for c in "error"]][:requests]
        while len(prompts) < requests:
            prompts.append(list(rs.randint(0, len(vocab), 5)))
        t1 = time.perf_counter()
        futs = [replica.submit(p, new_tokens) for p in prompts]
        recs = [f.result(timeout=300) for f in futs]
        ms = (time.perf_counter() - t1) * 1e3
        gens = sorted({r["generation"] for r in recs})
        text0 = "".join(itos[int(t)] for t in recs[0]["tokens"])
        print(f"{tag}: {len(recs)} requests in {ms:.0f} ms "
              f"(generation {gens}); 'the ' -> {text0!r}")
        return recs

    serve_round("serve v1")

    # keep training; commit; the replica hot-swaps between batches
    loss1 = train(net, loss_fn, trainer, data, steps, rs)
    ck.save(2, serving.state_for_serving(net))
    ck.wait()
    ck.close()
    print(f"trained {steps} more steps, loss {loss1:.3f}; "
          f"committed step 2")
    deadline = time.monotonic() + 30
    while replica.loaded_step != 2 and time.monotonic() < deadline:
        serve_round("serving while reloading")
        time.sleep(0.05)
    assert replica.loaded_step == 2, "hot reload never landed"
    recs = serve_round("serve v2 (hot-reloaded)")
    assert all(len(r["tokens"]) == new_tokens for r in recs)
    retraces = serving.trace_count() - traces
    print(f"hot reloads applied: {replica.reloads}; "
          f"retraces after warmup: {retraces}")
    assert retraces == 0
    replica.close()
    print("ok")


if __name__ == "__main__":
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--requests", type=int, default=8)
    ap.add_argument("--new-tokens", type=int, default=8)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()
    main(steps=args.steps, requests=args.requests,
         new_tokens=args.new_tokens, seed=args.seed)
