"""Character-level GPT on real English text (the Zen of Python).

The decoder-only family end-to-end: byte-tokenize a real corpus, train
``gpt_tiny`` with the shifted LM loss, then sample a continuation.
Mirrors the role example/rnn/word_lm plays in the reference, on the
transformer decoder instead of the LSTM.

Run: python examples/gpt_char_lm.py [--steps 200]
"""

import argparse
import codecs
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon.model_zoo import gpt


def corpus():
    """The Zen of Python — real English text shipped inside CPython
    (`this` module, rot13-encoded; importing it PRINTS the text, so
    swallow that side effect)."""
    import contextlib
    import io

    with contextlib.redirect_stdout(io.StringIO()):
        import this as this_mod

    return codecs.decode(this_mod.s, "rot13")


def main(steps=200, seq_len=64, batch=16, lr=3e-3, seed=0):
    text = corpus()
    vocab = sorted(set(text))
    stoi = {c: i for i, c in enumerate(vocab)}
    data = np.array([stoi[c] for c in text], np.int32)
    rs = np.random.RandomState(seed)

    net = gpt.gpt_tiny(vocab_size=len(vocab), units=64, num_layers=2,
                       num_heads=4, max_length=seq_len)
    net.initialize(init=mx.init.Xavier())
    loss_fn = gpt.GPTLMLoss()
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": lr})

    def sample_batch():
        starts = rs.randint(0, len(data) - seq_len - 1, batch)
        return np.stack([data[s:s + seq_len] for s in starts])

    first = last = None
    for step in range(steps):
        ids = nd.array(sample_batch().astype(np.float32))
        with autograd.record():
            loss = loss_fn(net(ids), ids)
        loss.backward()
        tr.step(batch)
        val = float(loss.asnumpy())
        first = first if first is not None else val
        last = val
        if step % 50 == 0:
            print(f"step {step}: nll/char {val:.3f}")

    print(f"nll/char {first:.3f} -> {last:.3f}")
    assert last < 0.7 * first, "LM failed to learn the corpus"

    seed_txt = "Beautiful is "
    seed_ids = nd.array(np.array([[stoi[c] for c in seed_txt]],
                                 np.float32))
    out = gpt.generate(net, seed_ids, max_new_tokens=40).asnumpy()[0]
    cont = "".join(vocab[int(i)] for i in out)
    print("sample:", repr(cont))
    print("char-LM OK")


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=200)
    ap.add_argument("--seq-len", type=int, default=64)
    args = ap.parse_args()
    main(steps=args.steps, seq_len=args.seq_len)
