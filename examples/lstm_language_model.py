#!/usr/bin/env python
"""Word-level LSTM language model (reference: example/rnn/word_lm —
the classic PTB LM config, on synthetic text when no corpus given).

Exercises the fused-scan RNN stack end to end: Embedding → stacked
LSTM (gluon.rnn.LSTM, lax.scan under hybridize) → tied-softmax
decoder, truncated BPTT with hidden-state carry, perplexity metric.

Run:  python examples/lstm_language_model.py --epochs 2
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn, rnn


class RNNModel(gluon.Block):
    def __init__(self, vocab_size, embed_dim, hidden, layers, dropout,
                 **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.drop = nn.Dropout(dropout)
            self.encoder = nn.Embedding(vocab_size, embed_dim)
            self.rnn = rnn.LSTM(hidden, num_layers=layers,
                                dropout=dropout, input_size=embed_dim)
            self.decoder = nn.Dense(vocab_size, in_units=hidden,
                                    flatten=False)

    def forward(self, inputs, hidden):
        emb = self.drop(self.encoder(inputs))
        output, hidden = self.rnn(emb, hidden)
        output = self.drop(output)
        return self.decoder(output), hidden

    def begin_state(self, *args, **kwargs):
        return self.rnn.begin_state(*args, **kwargs)


def synthetic_corpus(vocab, n_tokens, seed=0):
    """Markov-ish synthetic text so the LM has learnable structure."""
    rs = np.random.RandomState(seed)
    trans = rs.dirichlet(np.ones(vocab) * 0.1, size=vocab)
    toks = np.empty(n_tokens, np.int32)
    toks[0] = 0
    for i in range(1, n_tokens):
        toks[i] = rs.choice(vocab, p=trans[toks[i - 1]])
    return toks


def batchify(data, batch_size):
    n = len(data) // batch_size
    return data[:n * batch_size].reshape(batch_size, n).T  # (T, B)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--vocab", type=int, default=200)
    ap.add_argument("--embed", type=int, default=64)
    ap.add_argument("--hidden", type=int, default=128)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--bptt", type=int, default=16)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--lr", type=float, default=0.01)
    ap.add_argument("--tokens", type=int, default=8000)
    args = ap.parse_args()

    data = batchify(synthetic_corpus(args.vocab, args.tokens),
                    args.batch_size)
    if data.shape[0] <= args.bptt + 1:
        sys.exit(f"corpus too small: {data.shape[0]} rows after "
                 f"batchify(batch_size={args.batch_size}) but bptt="
                 f"{args.bptt} needs > bptt+1; add --tokens or shrink "
                 "--batch-size/--bptt")
    model = RNNModel(args.vocab, args.embed, args.hidden, args.layers,
                     dropout=0.2)
    model.initialize(init=mx.init.Xavier())
    trainer = gluon.Trainer(model.collect_params(), "adam",
                            {"learning_rate": args.lr,
                             "clip_gradient": 0.25})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    for epoch in range(args.epochs):
        hidden = model.begin_state(batch_size=args.batch_size)
        total_l, total_n = 0.0, 0
        for i in range(0, data.shape[0] - 1 - args.bptt, args.bptt):
            x = mx.nd.array(data[i:i + args.bptt].astype("float32"))
            y = mx.nd.array(
                data[i + 1:i + 1 + args.bptt].astype("float32"))
            # truncated BPTT: detach the carried state
            hidden = [h.detach() for h in hidden]
            with autograd.record():
                out, hidden = model(x, hidden)
                loss = loss_fn(out.reshape((-1, args.vocab)),
                               y.reshape((-1,)))
            loss.backward()
            trainer.step(args.bptt * args.batch_size)
            total_l += float(loss.sum().asnumpy())
            total_n += args.bptt * args.batch_size
        ppl = float(np.exp(total_l / total_n))
        print(f"epoch {epoch}: perplexity {ppl:.2f}")
    uniform_ppl = args.vocab
    print(f"final perplexity {ppl:.2f} vs uniform {uniform_ppl}")
    assert ppl < uniform_ppl, "LM failed to beat the uniform baseline"
    print("lstm_language_model OK")


if __name__ == "__main__":
    main()
