#!/usr/bin/env python
"""DCGAN on synthetic shapes (reference: example/gluon/dcgan.py — the
generative-adversarial tier of the example zoo).

Generator: Dense → stacked Conv2DTranspose to (3, 16, 16);
discriminator: conv stack → logit.  Trains on a synthetic "bright
disk" image distribution; asserts the adversarial game moves (D can't
collapse to always-right, G's samples move toward the data statistics).
Alternating eager steps — two optimizers, the reference's exact loop
shape — each side hybridized to one XLA program.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn

SIZE = 16


def build_generator(latent):
    g = nn.HybridSequential(prefix="gen_")
    with g.name_scope():
        g.add(nn.Dense(128 * 4 * 4, in_units=latent),
              nn.HybridLambda(
                  lambda F, x: F.reshape(x, shape=(-1, 128, 4, 4))),
              nn.BatchNorm(), nn.Activation("relu"),
              nn.Conv2DTranspose(64, 4, strides=2, padding=1),  # 8x8
              nn.BatchNorm(), nn.Activation("relu"),
              nn.Conv2DTranspose(3, 4, strides=2, padding=1),   # 16x16
              nn.Activation("sigmoid"))
    return g


def build_discriminator():
    d = nn.HybridSequential(prefix="disc_")
    with d.name_scope():
        d.add(nn.Conv2D(32, 4, strides=2, padding=1),
              nn.LeakyReLU(0.2),
              nn.Conv2D(64, 4, strides=2, padding=1),
              nn.BatchNorm(), nn.LeakyReLU(0.2),
              nn.Flatten(), nn.Dense(1))
    return d


def real_batch(rng, n):
    """Bright disks on dark background at random centers."""
    x = np.zeros((n, 3, SIZE, SIZE), np.float32)
    yy, xx = np.mgrid[0:SIZE, 0:SIZE]
    for i in range(n):
        cy, cx = rng.uniform(5, SIZE - 5, 2)
        r = rng.uniform(2.5, 4.5)
        mask = ((yy - cy) ** 2 + (xx - cx) ** 2) < r * r
        col = rng.uniform(0.7, 1.0, 3)
        for c in range(3):
            x[i, c][mask] = col[c]
    return x


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=120)
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--latent", type=int, default=16)
    parser.add_argument("--lr", type=float, default=2e-4)
    args = parser.parse_args()

    mx.random.seed(0)
    np.random.seed(0)
    rng = np.random.RandomState(0)

    netG = build_generator(args.latent)
    netD = build_discriminator()
    netG.initialize(init=mx.init.Normal(0.02))
    netD.initialize(init=mx.init.Normal(0.02))
    netG.hybridize()
    netD.hybridize()
    trainerG = gluon.Trainer(netG.collect_params(), "adam",
                             {"learning_rate": args.lr, "beta1": 0.5})
    trainerD = gluon.Trainer(netD.collect_params(), "adam",
                             {"learning_rate": args.lr, "beta1": 0.5})
    loss_fn = gluon.loss.SigmoidBinaryCrossEntropyLoss()

    B = args.batch_size
    ones = mx.nd.ones((B,))
    zeros = mx.nd.zeros((B,))
    errD = errG = None
    for step in range(args.steps):
        real = mx.nd.array(real_batch(rng, B))
        z = mx.nd.array(rng.randn(B, args.latent).astype(np.float32))
        # D step: real -> 1, fake -> 0 (fake through stop-gradient)
        with autograd.record():
            out_real = netD(real).reshape((-1,))
            fake = netG(z)
            out_fake = netD(fake.detach()).reshape((-1,))
            lossD = (loss_fn(out_real, ones)
                     + loss_fn(out_fake, zeros)).mean()
        lossD.backward()
        trainerD.step(B)
        # G step: fool D
        with autograd.record():
            fake = netG(z)
            lossG = loss_fn(netD(fake).reshape((-1,)), ones).mean()
        lossG.backward()
        trainerG.step(B)
        errD, errG = float(lossD.asnumpy()), float(lossG.asnumpy())
        if step % 30 == 0:
            print(f"step {step}: lossD {errD:.4f} lossG {errG:.4f}")

    # the game is live if D hasn't collapsed (both losses finite and
    # neither side at zero) and G's samples moved toward the data's
    # brightness statistics
    z = mx.nd.array(np.random.RandomState(7)
                    .randn(B, args.latent).astype(np.float32))
    with autograd.predict_mode():
        samples = netG(z).asnumpy()
    real_mean = real_batch(np.random.RandomState(7), B).mean()
    init_gap = abs(0.5 - real_mean)  # sigmoid init emits ~0.5 mean
    gap = abs(samples.mean() - real_mean)
    print(f"sample-mean gap to data: {gap:.3f} (untrained ~{init_gap:.3f})")
    ok = np.isfinite(errD) and np.isfinite(errG) and errD > 1e-3 \
        and gap < init_gap
    print("dcgan OK" if ok else "dcgan FAILED")
    if not ok:
        sys.exit(1)


if __name__ == "__main__":
    main()
