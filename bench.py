#!/usr/bin/env python
"""Benchmark entry point (driver contract: prints ONE JSON line).

Metric: ResNet-50 training throughput in samples/sec/chip (the BASELINE.md
headline).  The whole training step — forward, backward, SGD+momentum
update, BatchNorm stat updates — runs as ONE compiled XLA program
(parallel.ShardedTrainer) in bfloat16 compute on the MXU.

Round-2 hardening (VERDICT.md "Next round" #1/#2): the orchestrator
process never imports jax.  It runs the actual benchmark in a worker
subprocess with a time budget, falls back to smaller configs and then to
the CPU backend if TPU init fails or hangs, and ALWAYS prints exactly one
structured JSON line.  Workers use a persistent XLA compilation cache
(.jax_cache/) so the driver's run pays no recompile if the repo was
benched during the round.  An MFU estimate is included (analytic
FLOPs/sample ÷ device peak).

vs_baseline is null: BASELINE.json.published is {} (reference mount was
empty — see BASELINE.md provenance note).
"""

import json
import os
import subprocess
import sys
import time

_HOSTILE_ENV_PREFIXES = ("PALLAS_AXON", "AXON_", "TPU_", "LIBTPU")

# bf16 peak FLOP/s per chip by device kind substring (public specs)
_PEAK_FLOPS = [
    ("v6e", 918e12), ("v6", 918e12),
    ("v5p", 459e12), ("v5e", 197e12), ("v5 lite", 197e12),
    ("v4", 275e12), ("v3", 123e12), ("v2", 45e12),
]

# ResNet-50 @224: ~4.09e9 MACs fwd => 8.2e9 FLOPs; training ~= 3x fwd
_RESNET50_TRAIN_FLOPS_224 = 3.0 * 2 * 4.089e9


def _attempts():
    steps = int(os.environ.get("BENCH_STEPS", 20))
    budget = int(os.environ.get("BENCH_BUDGET", 560))
    tpu_attempts = [] if os.environ.get("BENCH_SKIP_TPU") else [
        (None, {"batch": int(os.environ.get("BENCH_BATCH", 256)),
                "image": int(os.environ.get("BENCH_IMAGE", 224)),
                "steps": steps, "backend": "tpu"}, budget),
        (None, {"batch": 64, "image": 224, "steps": 10, "backend": "tpu"},
         min(300, budget)),
    ]
    return tpu_attempts + [
        ({"JAX_PLATFORMS": "cpu"},
         {"batch": 8, "image": 32, "steps": 3, "backend": "cpu"}, 240),
    ]


def orchestrate():
    errors = []
    for env_over, cfg, budget in _attempts():
        env = dict(os.environ)
        if env_over is not None:
            # CPU fallback: strip anything that could claim the tunnel
            env = {k: v for k, v in env.items()
                   if not k.startswith(_HOSTILE_ENV_PREFIXES)}
            env.update(env_over)
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--worker",
                 json.dumps(cfg)],
                env=env, timeout=budget, capture_output=True, text=True)
        except subprocess.TimeoutExpired:
            errors.append(f"{cfg['backend']} b{cfg['batch']}: "
                          f"timeout {budget}s")
            continue
        line = None
        for ln in reversed(proc.stdout.strip().splitlines()):
            try:
                obj = json.loads(ln)
            except (ValueError, TypeError):
                continue
            if isinstance(obj, dict) and "metric" in obj:
                line = ln
                break
        if proc.returncode == 0 and line:
            print(line)
            return 0
        tail = (proc.stderr or proc.stdout or "").strip()[-300:]
        errors.append(f"{cfg['backend']} b{cfg['batch']}: rc="
                      f"{proc.returncode} {tail.splitlines()[-1] if tail else ''}")
    print(json.dumps({
        "metric": "resnet50_train_samples_per_sec_per_chip",
        "value": 0.0, "unit": "samples/sec/chip", "vs_baseline": None,
        "error": "; ".join(errors)[-500:],
    }))
    return 0


def worker(cfg):
    import jax

    # backend init guard: one retry, then a distinct rc for the parent
    devices = None
    for attempt in range(2):
        try:
            devices = jax.devices()
            break
        except RuntimeError as e:
            sys.stderr.write(f"backend init failed ({e}); "
                             f"attempt {attempt}\n")
            time.sleep(8)
    if devices is None:
        sys.exit(3)
    if cfg["backend"] != "cpu" and devices[0].platform == "cpu":
        # jax fell back to CPU on a chip-less host: don't burn the TPU
        # attempt's budget running ResNet-50 on CPU — bail so the parent
        # moves straight to the sized-for-CPU fallback config
        sys.stderr.write("requested TPU but only CPU available\n")
        sys.exit(4)

    # persistent compile cache so the driver's bench run pays no
    # recompile; TPU only (XLA:CPU AOT caches are host-specific)
    if devices[0].platform != "cpu":
        cache_dir = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), ".jax_cache")
        try:
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 0.0)
            jax.config.update(
                "jax_persistent_cache_min_entry_size_bytes", -1)
        except Exception:
            pass  # cache is best-effort

    import numpy as np

    import jax.numpy as jnp

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, parallel
    from mxnet_tpu.gluon.model_zoo import vision

    n_chips = max(1, len(devices))
    batch_size, image_size, steps = cfg["batch"], cfg["image"], cfg["steps"]

    net = vision.resnet50_v1(classes=1000)
    net.initialize(init=mx.init.Xavier())
    net.cast("bfloat16")

    mesh = parallel.data_parallel_mesh(n_chips)
    trainer = parallel.ShardedTrainer(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        {"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4}, mesh=mesh)

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.standard_normal(
        (batch_size, 3, image_size, image_size)), dtype=jnp.bfloat16)
    y = jnp.asarray(rng.randint(0, 1000, batch_size).astype("float32"))

    # warmup / compile
    trainer.step(x, y).wait_to_read()
    trainer.step(x, y).wait_to_read()

    t0 = time.perf_counter()
    loss = None
    for _ in range(steps):
        loss = trainer.step(x, y)
    loss.wait_to_read()
    dt = time.perf_counter() - t0

    samples_per_sec = batch_size * steps / dt
    per_chip = samples_per_sec / n_chips

    kind = getattr(devices[0], "device_kind", "") or ""
    peak = None
    for key, val in _PEAK_FLOPS:
        if key in kind.lower():
            peak = val
            break
    flops_per_sample = (_RESNET50_TRAIN_FLOPS_224
                        * (image_size / 224.0) ** 2)
    mfu = (round(per_chip * flops_per_sample / peak, 4)
           if peak else None)

    print(json.dumps({
        "metric": "resnet50_train_samples_per_sec_per_chip",
        "value": round(per_chip, 2),
        "unit": "samples/sec/chip",
        "vs_baseline": None,
        "mfu": mfu,
        "device_kind": kind,
        "backend": devices[0].platform,
        "batch": batch_size,
        "image": image_size,
    }))


if __name__ == "__main__":
    if len(sys.argv) > 2 and sys.argv[1] == "--worker":
        worker(json.loads(sys.argv[2]))
    else:
        sys.exit(orchestrate())
